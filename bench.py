"""Benchmark: Llama training step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "train_tokens_per_sec_per_chip", "value": N, "unit": "tok/s",
   "vs_baseline": MFU/0.40, ...}

The baseline target is the north star from BASELINE.json: >=40% MFU on the
Llama fine-tune path (the reference has no in-repo number for this — 40% MFU
is the bar it sets). vs_baseline > 1.0 means above-target MFU.
"""

import json
import time

import jax
import jax.numpy as jnp

# Peak FLOP/s now live in ray_tpu/accelerators/flops.py — ONE table
# shared with the live MFU gauge (_internal/accel.py), re-exported here
# for callers that historically imported them from bench.
from ray_tpu.accelerators.flops import PEAK_FLOPS, peak_flops  # noqa: F401


def main():
    import os

    import optax

    from ray_tpu.models import LlamaConfig, LlamaModel, cross_entropy_loss
    from ray_tpu.parallel import (MeshConfig, create_train_state,
                                  default_optimizer, make_train_step)

    on_tpu = jax.default_backend() == "tpu"
    big = not os.environ.get("RTPU_BENCH_SMALL")
    if on_tpu and big:
        # ~2.65B params (VERDICT r3 item 5: push past 2.5B with remat).
        # Memory budget on one v5e (16 GB HBM): bf16 params 5.3 GB +
        # bf16 donated grads 5.3 GB + adafactor factored stats (fp32
        # row/col vectors, ~MBs) + remat'd activations. fp32 params
        # would be 10.6+10.6 GB and spill — bf16 params with
        # adafactor's fp32 factored accumulators is the T5X-lineage
        # memory-frugal configuration.
        config = LlamaConfig(
            vocab_size=32000, hidden_size=2560, intermediate_size=6912,
            num_layers=32, num_heads=20, num_kv_heads=20,
            max_seq_len=2048, param_dtype=jnp.bfloat16)
        batch, seq, steps = 2, 2048, 10
        tx = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adafactor(learning_rate=1e-3))
    elif on_tpu:
        # RTPU_BENCH_SMALL=1 fallback: ~1.26B params (the round-3
        # headline config). 16 heads of head_dim=128 keep the MXU's
        # 128-wide contraction full. Memory budget on one v5e (16 GB HBM):
        # fp32 params 5.0 GB + adafactor's factored second moments (~row+
        # col vectors, MBs) + remat'd activations + donated bf16 grads.
        # AdamW's m/v would add +10 GB and spill; adafactor is the
        # standard TPU memory-frugal choice (T5/PaLM lineage).
        config = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=22, num_heads=16, num_kv_heads=16, max_seq_len=2048)
        batch, seq, steps = 4, 2048, 12
        tx = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adafactor(learning_rate=1e-3))
    else:
        config = LlamaConfig.tiny_test()
        batch, seq, steps = 4, 256, 5
        tx = default_optimizer(total_steps=1000)

    mesh = MeshConfig(data=-1).build()
    model = LlamaModel(config)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    state = create_train_state(
        jax.random.PRNGKey(0), model, tokens, mesh, tx)

    def loss_fn(params, batch_data):
        logits = model.apply({"params": params}, batch_data["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch_data["tokens"][:, 1:])

    train_step = make_train_step(loss_fn, mesh, state=state)
    rng = jax.random.PRNGKey(1)
    data = {"tokens": jax.random.randint(rng, (batch, seq), 0,
                                         config.vocab_size)}

    from ray_tpu._internal import accel

    with mesh:
        # Warmup / compile. NOTE: fence with device_get of a scalar, not
        # block_until_ready — some PJRT transports (e.g. relayed remote
        # execution) resolve buffer readiness at dispatch time.
        # The accel plane's compile tracker is installed before warmup
        # so the compile lands in rtpu_xla_compile_seconds_total.
        accel.ensure_installed()
        compile_t0 = time.perf_counter()
        state, metrics = train_step(state, data)
        float(jax.device_get(metrics["loss"]))
        warmup_s = time.perf_counter() - compile_t0
        start = time.perf_counter()
        for _ in range(steps):
            state, metrics = train_step(state, data)
        final_loss = float(jax.device_get(metrics["loss"]))
        elapsed = time.perf_counter() - start

    n_devices = jax.device_count()
    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / elapsed
    tokens_per_sec_per_chip = tokens_per_sec / n_devices

    n_params = config.num_params()
    flops_per_token = 6 * n_params + 12 * config.num_layers * seq * \
        config.hidden_size
    achieved = tokens_per_sec_per_chip * flops_per_token
    peak = peak_flops(jax.devices()[0])
    mfu = achieved / peak

    # Feed the live accelerator plane the same numbers the JSON line
    # reports: the rtpu_step_mfu gauge and the bench's offline MFU now
    # share both the FLOP model and the peak-FLOPs denominator.
    accel.report_step(
        "bench_train", elapsed, steps=steps,
        tokens=tokens_per_step * steps,
        device_s=elapsed,  # the loop is device-bound end to end
        flops=float(flops_per_token) * tokens_per_step * steps
        / n_devices,
        device_kind=getattr(jax.devices()[0], "device_kind", "cpu"))

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 1),
        "unit": "tok/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "mfu": round(mfu, 4),
        "model_params": n_params,
        "batch": batch, "seq": seq, "steps": steps,
        "backend": jax.default_backend(),
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "loss": round(final_loss, 4),
        "warmup_s": round(warmup_s, 2),
        # jax.monitoring-attributed compile time (accel plane tracker)
        "xla_compile_s": round(accel.compile_seconds_total(), 2),
    }))


def dryrun_7b(n_devices: int = 8, run_step: bool = True):
    """The 7B north-star config sharded over an n-device mesh
    (BASELINE.json config 3: Llama-2-7B fine-tune), dryrun-grade on the
    virtual CPU mesh: AOT-compile the full SPMD train step (fsdp x data
    sharding with remat + adafactor), report XLA's PER-DEVICE memory
    accounting from the compiled executable, and optionally execute one
    real step for wall-clock. Run with:
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      JAX_PLATFORMS=cpu python bench.py --dryrun7b
    """
    import optax

    from ray_tpu.models import LlamaConfig, LlamaModel, cross_entropy_loss
    from ray_tpu.parallel import (MeshConfig, create_train_state,
                                  make_train_step)

    import dataclasses
    # bf16 params (see the single-chip big config): 7B fp32 would be
    # 26 GB/device unsharded; fsdp over 8 shards the 13 GB bf16 tree to
    # ~1.7 GB/device + adafactor factored stats.
    config = dataclasses.replace(LlamaConfig.llama2_7b(),
                                 param_dtype=jnp.bfloat16)
    batch, seq = n_devices, 2048
    mesh = MeshConfig(fsdp=n_devices // 2, data=2).build()
    model = LlamaModel(config)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    tx = optax.chain(optax.clip_by_global_norm(1.0),
                     optax.adafactor(learning_rate=1e-4))
    t0 = time.perf_counter()
    state = create_train_state(
        jax.random.PRNGKey(0), model, tokens, mesh, tx)
    init_s = time.perf_counter() - t0

    def loss_fn(params, batch_data):
        logits = model.apply({"params": params}, batch_data["tokens"])
        return cross_entropy_loss(logits[:, :-1],
                                  batch_data["tokens"][:, 1:])

    train_step = make_train_step(loss_fn, mesh, state=state)
    data = {"tokens": jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, config.vocab_size)}
    with mesh:
        t0 = time.perf_counter()
        lowered = train_step.lower(state, data)
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        mem = compiled.memory_analysis()
        per_device = {
            "argument_bytes": getattr(mem, "argument_size_in_bytes", None),
            "output_bytes": getattr(mem, "output_size_in_bytes", None),
            "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
            "peak_bytes": getattr(
                mem, "peak_memory_in_bytes",
                getattr(mem, "temp_size_in_bytes", None)),
        }
        step_s = None
        loss = None
        if run_step:
            t0 = time.perf_counter()
            state, metrics = compiled(state, data)
            loss = float(jax.device_get(metrics["loss"]))
            step_s = time.perf_counter() - t0
    print(json.dumps({
        "metric": "llama7b_dryrun_mesh",
        "model_params": config.num_params(),
        "mesh": {"fsdp": n_devices // 2, "data": 2},
        "n_devices": n_devices,
        "batch": batch, "seq": seq,
        "init_s": round(init_s, 1),
        "compile_s": round(compile_s, 1),
        "step_s": round(step_s, 1) if step_s is not None else None,
        "loss": round(loss, 4) if loss is not None else None,
        "per_device_memory": per_device,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    import sys
    if "--dryrun7b" in sys.argv:
        dryrun_7b(run_step="--no-step" not in sys.argv)
    else:
        main()
