"""Benchmark: Llama training step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "train_tokens_per_sec_per_chip", "value": N, "unit": "tok/s",
   "vs_baseline": MFU/0.40, ...}

The baseline target is the north star from BASELINE.json: >=40% MFU on the
Llama fine-tune path (the reference has no in-repo number for this — 40% MFU
is the bar it sets). vs_baseline > 1.0 means above-target MFU.
"""

import json
import time

import jax
import jax.numpy as jnp

# Peak FLOP/s now live in ray_tpu/accelerators/flops.py — ONE table
# shared with the live MFU gauge (_internal/accel.py), re-exported here
# for callers that historically imported them from bench.
from ray_tpu.accelerators.flops import PEAK_FLOPS, peak_flops  # noqa: F401


def main():
    import os

    import optax

    from ray_tpu.models import LlamaConfig, LlamaModel, cross_entropy_loss
    from ray_tpu.parallel import (MeshConfig, create_train_state,
                                  default_optimizer, make_train_step)

    on_tpu = jax.default_backend() == "tpu"
    big = not os.environ.get("RTPU_BENCH_SMALL")
    if on_tpu and big:
        # ~2.65B params (VERDICT r3 item 5: push past 2.5B with remat).
        # Memory budget on one v5e (16 GB HBM): bf16 params 5.3 GB +
        # bf16 donated grads 5.3 GB + adafactor factored stats (fp32
        # row/col vectors, ~MBs) + remat'd activations. fp32 params
        # would be 10.6+10.6 GB and spill — bf16 params with
        # adafactor's fp32 factored accumulators is the T5X-lineage
        # memory-frugal configuration.
        config = LlamaConfig(
            vocab_size=32000, hidden_size=2560, intermediate_size=6912,
            num_layers=32, num_heads=20, num_kv_heads=20,
            max_seq_len=2048, param_dtype=jnp.bfloat16)
        batch, seq, steps = 2, 2048, 10
        tx = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adafactor(learning_rate=1e-3))
    elif on_tpu:
        # RTPU_BENCH_SMALL=1 fallback: ~1.26B params (the round-3
        # headline config). 16 heads of head_dim=128 keep the MXU's
        # 128-wide contraction full. Memory budget on one v5e (16 GB HBM):
        # fp32 params 5.0 GB + adafactor's factored second moments (~row+
        # col vectors, MBs) + remat'd activations + donated bf16 grads.
        # AdamW's m/v would add +10 GB and spill; adafactor is the
        # standard TPU memory-frugal choice (T5/PaLM lineage).
        config = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=22, num_heads=16, num_kv_heads=16, max_seq_len=2048)
        batch, seq, steps = 4, 2048, 12
        tx = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adafactor(learning_rate=1e-3))
    else:
        config = LlamaConfig.tiny_test()
        batch, seq, steps = 4, 256, 5
        tx = default_optimizer(total_steps=1000)

    mesh = MeshConfig(data=-1).build()
    model = LlamaModel(config)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    state = create_train_state(
        jax.random.PRNGKey(0), model, tokens, mesh, tx)

    def loss_fn(params, batch_data):
        logits = model.apply({"params": params}, batch_data["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch_data["tokens"][:, 1:])

    train_step = make_train_step(loss_fn, mesh, state=state)
    rng = jax.random.PRNGKey(1)
    data = {"tokens": jax.random.randint(rng, (batch, seq), 0,
                                         config.vocab_size)}

    from ray_tpu._internal import accel

    with mesh:
        # Warmup / compile. NOTE: fence with device_get of a scalar, not
        # block_until_ready — some PJRT transports (e.g. relayed remote
        # execution) resolve buffer readiness at dispatch time.
        # The accel plane's compile tracker is installed before warmup
        # so the compile lands in rtpu_xla_compile_seconds_total.
        accel.ensure_installed()
        compile_t0 = time.perf_counter()
        state, metrics = train_step(state, data)
        float(jax.device_get(metrics["loss"]))
        warmup_s = time.perf_counter() - compile_t0
        start = time.perf_counter()
        for _ in range(steps):
            state, metrics = train_step(state, data)
        final_loss = float(jax.device_get(metrics["loss"]))
        elapsed = time.perf_counter() - start

    n_devices = jax.device_count()
    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / elapsed
    tokens_per_sec_per_chip = tokens_per_sec / n_devices

    n_params = config.num_params()
    flops_per_token = 6 * n_params + 12 * config.num_layers * seq * \
        config.hidden_size
    achieved = tokens_per_sec_per_chip * flops_per_token
    peak = peak_flops(jax.devices()[0])
    mfu = achieved / peak

    # Feed the live accelerator plane the same numbers the JSON line
    # reports: the rtpu_step_mfu gauge and the bench's offline MFU now
    # share both the FLOP model and the peak-FLOPs denominator.
    accel.report_step(
        "bench_train", elapsed, steps=steps,
        tokens=tokens_per_step * steps,
        device_s=elapsed,  # the loop is device-bound end to end
        flops=float(flops_per_token) * tokens_per_step * steps
        / n_devices,
        device_kind=getattr(jax.devices()[0], "device_kind", "cpu"))

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 1),
        "unit": "tok/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "mfu": round(mfu, 4),
        "model_params": n_params,
        "batch": batch, "seq": seq, "steps": steps,
        "backend": jax.default_backend(),
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "loss": round(final_loss, 4),
        "warmup_s": round(warmup_s, 2),
        # jax.monitoring-attributed compile time (accel plane tracker)
        "xla_compile_s": round(accel.compile_seconds_total(), 2),
    }))


def dryrun_7b(n_devices: int = 8, run_step: bool = True):
    """The 7B north-star config sharded over an n-device mesh
    (BASELINE.json config 3: Llama-2-7B fine-tune), dryrun-grade on the
    virtual CPU mesh: AOT-compile the full SPMD train step (fsdp x data
    sharding with remat + adafactor), report XLA's PER-DEVICE memory
    accounting from the compiled executable, and optionally execute one
    real step for wall-clock. Run with:
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      JAX_PLATFORMS=cpu python bench.py --dryrun7b
    """
    import optax

    from ray_tpu.models import LlamaConfig, LlamaModel, cross_entropy_loss
    from ray_tpu.parallel import (MeshConfig, create_train_state,
                                  make_train_step)

    import dataclasses
    # bf16 params (see the single-chip big config): 7B fp32 would be
    # 26 GB/device unsharded; fsdp over 8 shards the 13 GB bf16 tree to
    # ~1.7 GB/device + adafactor factored stats.
    config = dataclasses.replace(LlamaConfig.llama2_7b(),
                                 param_dtype=jnp.bfloat16)
    batch, seq = n_devices, 2048
    mesh = MeshConfig(fsdp=n_devices // 2, data=2).build()
    model = LlamaModel(config)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    tx = optax.chain(optax.clip_by_global_norm(1.0),
                     optax.adafactor(learning_rate=1e-4))
    t0 = time.perf_counter()
    state = create_train_state(
        jax.random.PRNGKey(0), model, tokens, mesh, tx)
    init_s = time.perf_counter() - t0

    def loss_fn(params, batch_data):
        logits = model.apply({"params": params}, batch_data["tokens"])
        return cross_entropy_loss(logits[:, :-1],
                                  batch_data["tokens"][:, 1:])

    train_step = make_train_step(loss_fn, mesh, state=state)
    data = {"tokens": jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, config.vocab_size)}
    with mesh:
        t0 = time.perf_counter()
        lowered = train_step.lower(state, data)
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        mem = compiled.memory_analysis()
        per_device = {
            "argument_bytes": getattr(mem, "argument_size_in_bytes", None),
            "output_bytes": getattr(mem, "output_size_in_bytes", None),
            "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
            "peak_bytes": getattr(
                mem, "peak_memory_in_bytes",
                getattr(mem, "temp_size_in_bytes", None)),
        }
        step_s = None
        loss = None
        if run_step:
            t0 = time.perf_counter()
            state, metrics = compiled(state, data)
            loss = float(jax.device_get(metrics["loss"]))
            step_s = time.perf_counter() - t0
    print(json.dumps({
        "metric": "llama7b_dryrun_mesh",
        "model_params": config.num_params(),
        "mesh": {"fsdp": n_devices // 2, "data": 2},
        "n_devices": n_devices,
        "batch": batch, "seq": seq,
        "init_s": round(init_s, 1),
        "compile_s": round(compile_s, 1),
        "step_s": round(step_s, 1) if step_s is not None else None,
        "loss": round(loss, 4) if loss is not None else None,
        "per_device_memory": per_device,
        "backend": jax.default_backend(),
    }))


# ---------------------------------------------------------------------------
# multi-chip training plane: rank-Python-DP vs GSPMD vs MPMD pipeline
# (ROADMAP item 1; run `bench.py --multichip` — records MULTICHIP_r06-
# style rows; `--dryrun7b` appends the GSPMD parity gate + the 7B
# ZeRO-1 AOT memory accounting)
# ---------------------------------------------------------------------------

_RESPAWN_MARK = "_RTPU_BENCH_RESPAWNED"


def _ensure_virtual_devices(n: int) -> bool:
    """Re-exec (same argv) under an n-device virtual CPU mesh when this
    process has fewer devices. Returns True when the CURRENT process
    should run."""
    import os
    import subprocess
    import sys
    try:
        have = len(jax.devices())
    except RuntimeError:
        have = 0
    if have >= n:
        return True
    if os.environ.get(_RESPAWN_MARK) == "1":
        raise RuntimeError(f"need {n} devices, found {have} after respawn")
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                   f" --xla_force_host_platform_device_count={n}"),
        PYTHONPATH=os.pathsep.join(
            p for p in (here, os.environ.get("PYTHONPATH")) if p),
        **{_RESPAWN_MARK: "1"})
    subprocess.run([sys.executable, os.path.abspath(__file__)]
                   + sys.argv[1:], env=env, cwd=here, check=True)
    return False


# The shared A/B model: L residual tanh blocks over width D. Every arm
# (dp/two-level/gspmd/pipeline and the single-process reference) trains
# the SAME math from the same seeds, so loss columns are comparable.
_AB = {"width": 128, "hidden": 256, "blocks": 4, "batch": 64,
       "steps": 6, "lr": 1e-2}


def _ab_block_params(rng, width, hidden):
    import numpy as np
    return {"w1": (rng.randn(width, hidden) / np.sqrt(width)
                   ).astype("float32"),
            "w2": (rng.randn(hidden, width) / np.sqrt(hidden)
                   ).astype("float32")}


def _ab_model_fn():
    import flax.linen as nn
    import jax.numpy as jnp

    cfg = _AB

    class Blocks(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(cfg["blocks"]):
                h = nn.Dense(cfg["hidden"])(x)
                x = x + nn.Dense(cfg["width"])(jnp.tanh(h))
            return nn.Dense(1)(x)

    return Blocks()


def _ab_loss_fn(model, params, batch):
    import jax.numpy as jnp
    pred = model.apply({"params": params}, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2)


def _ab_batch_fn(step, rank, world):
    import numpy as np
    rng = np.random.RandomState(1000 + step)
    x = rng.randn(_AB["batch"], _AB["width"]).astype(np.float32)
    y = rng.randn(_AB["batch"], 1).astype(np.float32)
    if world > 1:
        per = _AB["batch"] // world
        sl = slice(rank * per, (rank + 1) * per)
        return {"x": x[sl], "y": y[sl]}
    return {"x": x, "y": y}


def _ab_flops_per_step() -> float:
    # 6x params-touched per token-row (fwd 2x + bwd 4x), dense layers
    cfg = _AB
    per_row = 2 * (cfg["width"] * cfg["hidden"] * 2 * cfg["blocks"]
                   + cfg["width"])
    return 6.0 * per_row * cfg["batch"] / 2.0


def _ab_spec(schedule: str, steps: int, quant: str = None):
    from ray_tpu.parallel.spmd import Zero1Hyper
    from ray_tpu.train import GSPMDTrainSpec
    return GSPMDTrainSpec(
        model_fn=_ab_model_fn, loss_fn=_ab_loss_fn, batch_fn=_ab_batch_fn,
        steps=steps, hyper=Zero1Hyper(learning_rate=_AB["lr"]),
        tokens_per_step=_AB["batch"], flops_per_step=_ab_flops_per_step(),
        schedule=schedule, collective_quant=quant)


def _ab_trainer_arm(schedule: str, num_workers: int, steps: int,
                    quant: str = None, label: str = None) -> dict:
    """One JaxTrainer arm; returns the rank-0 final report + timing."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    trainer = JaxTrainer(
        _ab_train_loop_entry,
        train_loop_config={"spec": _ab_spec(schedule, steps, quant)},
        scaling_config=ScalingConfig(
            num_workers=num_workers,
            mesh_axes={"data": 2, "fsdp": 4},
            dcn_axes=("data",), num_slices=2,
            virtual_devices=8),
        run_config=RunConfig(storage_path="/tmp/rtpu-multichip-bench"))
    result = trainer.fit()
    if result.error is not None:
        raise result.error
    m = result.metrics
    wall = float(m.get("wall_s") or 0.0)
    compile_s = float((m.get("goodput") or {}).get("compile_s") or 0.0)
    return {
        "arm": label or schedule, "workers": num_workers, "steps": steps,
        "losses": m.get("losses"), "loss": m.get("loss"),
        "wall_s": round(wall, 3),
        "compile_s": round(compile_s, 3),
        "steady_step_s": round(max(0.0, wall - compile_s) / steps, 4),
        "tokens_per_s": round(
            _AB["batch"] * steps / max(1e-9, wall - compile_s), 1),
        "mfu": m.get("mfu"),
        "goodput": m.get("goodput"),
        "collective_bytes": m.get("collective_bytes"),
        "collective_algo": m.get("collective_algo"),
    }


def _ab_train_loop_entry(config):
    from ray_tpu.train import gspmd_train_loop
    return gspmd_train_loop(config)


def _ab_stage_init(stage_index, num_stages):
    """Pipeline split of the SAME blocks model: stage 0 = first half of
    the residual blocks, last stage = second half + head. Seeds match
    _ab_model_fn's flax init? No — flax init order differs; the
    pipeline arm is gated against its OWN fused single-process
    reference (same stage params), not against the flax arms' losses."""
    import numpy as np
    import jax.numpy as jnp

    cfg = _AB
    rng = np.random.RandomState(7 + stage_index)
    per = cfg["blocks"] // num_stages
    blocks = [_ab_block_params(rng, cfg["width"], cfg["hidden"])
              for _ in range(per)]
    params = {"blocks": [
        {k: jnp.asarray(v) for k, v in b.items()} for b in blocks]}
    if stage_index == num_stages - 1:
        params["head"] = jnp.asarray(
            (rng.randn(cfg["width"], 1) / np.sqrt(cfg["width"])
             ).astype("float32"))

    is_last = stage_index == num_stages - 1

    def apply_fn(p, x):
        for b in p["blocks"]:
            x = x + jnp.tanh(x @ b["w1"]) @ b["w2"]
        if is_last:
            return x @ p["head"]
        return x

    return apply_fn, params


def _ab_pipeline_loss(y, targets):
    import jax.numpy as jnp
    return jnp.mean((y - jnp.asarray(targets)) ** 2)


def _pipeline_reference(num_stages: int, steps: int, microbatches: int):
    """Fused single-process twin of the pipeline arm: same per-stage
    params, same microbatch grad averaging, same AdamW — the parity
    reference for the MPMD schedule."""
    import numpy as np
    import optax

    stages = [_ab_stage_init(s, num_stages) for s in range(num_stages)]
    params = [p for _, p in stages]
    applies = [fn for fn, _ in stages]

    def full_loss(params, x, y):
        h = x
        for fn, p in zip(applies, params):
            h = fn(p, h)
        return _ab_pipeline_loss(h, y)

    tx = optax.adamw(_AB["lr"])
    opt_state = tx.init(params)
    step_fn = jax.jit(lambda p, o, x, y: _ref_step(tx, full_loss, p, o,
                                                   x, y, microbatches))
    losses = []
    for i in range(steps):
        batch = _ab_batch_fn(i, 0, 1)
        p_new, opt_state, loss = step_fn(params, opt_state,
                                         batch["x"], batch["y"])
        params = p_new
        losses.append(float(np.asarray(loss)))
    return losses


def _ref_step(tx, full_loss, params, opt_state, x, y, microbatches):
    import jax.numpy as jnp
    import optax

    xs = jnp.reshape(x, (microbatches, -1) + x.shape[1:])
    ys = jnp.reshape(y, (microbatches, -1) + y.shape[1:])

    def grad_one(mb):
        return jax.value_and_grad(lambda p: full_loss(p, xs[mb], ys[mb])
                                  )(params)

    losses, grads = [], None
    for mb in range(microbatches):
        loss_mb, g = grad_one(mb)
        losses.append(loss_mb)
        grads = g if grads is None else jax.tree_util.tree_map(
            jnp.add, grads, g)
    grads = jax.tree_util.tree_map(lambda g: g / microbatches, grads)
    updates, opt_state = tx.update(grads, opt_state, params)
    return (optax.apply_updates(params, updates), opt_state,
            jnp.mean(jnp.stack(losses)))


def _ab_pipeline_arm(steps: int, num_stages: int = 2,
                     microbatches: int = 4) -> dict:
    import numpy as np

    from ray_tpu.train import MPMDPipeline

    ref_losses = _pipeline_reference(num_stages, steps, microbatches)
    pipe = MPMDPipeline(_ab_stage_init, num_stages=num_stages,
                        loss_fn=_ab_pipeline_loss,
                        microbatches=microbatches,
                        hyper_kwargs={"learning_rate": _AB["lr"]})
    try:
        losses = []
        # round 0 pays the stage compiles; measure the steady window
        batch0 = _ab_batch_fn(0, 0, 1)
        losses.append(pipe.step(batch0["x"], batch0["y"])["loss"])
        pipe.reset_window()
        t0 = time.perf_counter()
        for i in range(1, steps):
            batch = _ab_batch_fn(i, 0, 1)
            losses.append(pipe.step(batch["x"], batch["y"])["loss"])
        steady = time.perf_counter() - t0
        bubble = pipe.bubble_report()
    finally:
        pipe.teardown()
    deltas = [abs(a - b) for a, b in zip(losses, ref_losses)]
    return {
        "arm": "pipeline", "workers": num_stages, "steps": steps,
        "microbatches": microbatches,
        "losses": [round(x, 6) for x in losses],
        "loss": losses[-1],
        "ref_losses": [round(x, 6) for x in ref_losses],
        "parity_max_delta": max(deltas),
        "steady_step_s": round(steady / max(1, steps - 1), 4),
        "tokens_per_s": round(
            _AB["batch"] * (steps - 1) / max(1e-9, steady), 1),
        "bubble_fraction": bubble["bubble_fraction"],
        "bubble_theoretical": bubble["bubble_theoretical"],
        "bubble_serial_floor": bubble["bubble_serial_floor"],
        "host_roundtrips": bubble["host_roundtrips"],
        "device_pulls": bubble["device_pulls"],
    }


class _FlightDeckRank:
    """One rank of the straggler/SLO-alert demo (plain class; wrapped
    with ray_tpu.remote inside _flight_deck_demo)."""

    def __init__(self, rank, world, group):
        self.rank, self.world, self.group = rank, world, group

    def join(self, chaos_spec=""):
        if chaos_spec:
            # arm THIS process's chaos registry: every incoming
            # collective hop is delayed, making this rank late into
            # every subsequent op — the seeded straggler
            from ray_tpu._internal.chaos import REGISTRY
            REGISTRY.arm(spec=chaos_spec, seed=7)
        from ray_tpu.util.collective import collective as col
        col.init_collective_group(self.world, self.rank,
                                  group_name=self.group)
        return True

    def run_ops(self, ops):
        import numpy as np

        from ray_tpu.util.collective import collective as col
        for _ in range(ops):
            col.allreduce(np.arange(64, dtype=np.int64),
                          group_name=self.group)
        summary = col._group(self.group).straggler_summary()
        return summary

    def flush(self):
        from ray_tpu.train import steptrace
        from ray_tpu.util import metrics
        steptrace.flush()
        return metrics.flush_now()

    def leave(self):
        from ray_tpu.util.collective import collective as col
        col.destroy_collective_group(self.group)
        return True


def _flight_deck_demo(ops: int = 8, delay_s: float = 0.05) -> dict:
    """Deterministic straggler + SLO-alert e2e on the live cluster:
    four collective ranks; rank 1 arms a prob-1.0 chaos delay on its
    incoming collective hops (fixed seed — nothing is time-seeded), so
    it enters every op ~delay_s late. Rank 0 — the star root, the only
    rank that hears from several peers — attributes the skew to rank 1
    and emits STRAGGLER_DETECTED; one alert-engine pass over the
    cluster's flushed metrics then trips the collective-wait p95 SLO.
    Both surfaces land in the GCS (cli stragglers / cli alerts /
    /api/alerts)."""
    import ray_tpu
    from ray_tpu._internal.alerts import AlertEngine, default_rules
    from ray_tpu._internal.core_worker import get_core_worker
    from ray_tpu.train.steptrace import steptrace_disabled
    from ray_tpu.util import state as st
    from ray_tpu.util.metrics import collect_cluster_metrics

    world = 4
    group = "flightdeck-demo"
    rank_cls = ray_tpu.remote(num_cpus=1)(_FlightDeckRank)
    actors = [rank_cls.remote(r, world, group) for r in range(world)]
    spec = f"collective_msg:delay:1.0:{delay_s}"
    ray_tpu.get([a.join.remote(spec if r == 1 else "")
                 for r, a in enumerate(actors)], timeout=120)
    summaries = ray_tpu.get([a.run_ops.remote(ops) for a in actors],
                            timeout=300)
    ray_tpu.get([a.flush.remote() for a in actors], timeout=60)
    stragglers = st.stragglers()
    engine = AlertEngine(rules=default_rules())
    fired = engine.evaluate_once(
        snapshots=collect_cluster_metrics(get_core_worker().gcs))
    alert_rows = st.alerts()
    try:
        ray_tpu.get([a.leave.remote() for a in actors], timeout=60)
    except Exception:
        pass
    for a in actors:
        ray_tpu.kill(a)
    return {
        "chaos_spec": spec,
        "ops": ops,
        "steptrace_disabled": steptrace_disabled(),
        "straggler_events": [
            {k: e.get(k) for k in ("rank", "phase", "observer_rank",
                                   "wait_s", "median_others_s")}
            for e in stragglers["events"]],
        "observer_summary": summaries[0],
        "alerts_fired": [f["rule"] for f in fired],
        "alert_table_rules": sorted({a["rule"] for a in alert_rows}),
    }


def multichip_ab(steps: int = 6, out_path: str = None) -> dict:
    """The multi-chip A/B: rank-Python DP baseline vs two-level GSPMD
    vs whole-mesh GSPMD (ZeRO-1) vs MPMD pipeline, all on the emulated
    two-slice 8-device topology. Single-core caveat: arms that rely on
    overlap (pipeline) or on deleting Python turnarounds (gspmd) show
    their structure here and their full wall-clock win only with real
    parallel cores/chips."""
    import os

    import ray_tpu
    from ray_tpu.train import run_single_process_baseline

    if not _ensure_virtual_devices(8):
        return {}
    baseline = run_single_process_baseline(_ab_spec("auto", steps))
    ray_tpu.init(num_cpus=8, object_store_memory=300 * 1024 * 1024)
    try:
        rows = [
            _ab_trainer_arm("dp", num_workers=2, steps=steps),
            _ab_trainer_arm("two_level", num_workers=2, steps=steps),
            _ab_trainer_arm("two_level", num_workers=2, steps=steps,
                            quant="int8", label="two_level_int8"),
            _ab_trainer_arm("gspmd", num_workers=1, steps=steps),
            _ab_pipeline_arm(steps),
        ]
        # -- train-plane flight deck --------------------------------------
        # (1) the cross-rank step timeline the arms just flushed;
        # (2) the seeded straggler + SLO-alert e2e
        from ray_tpu.util import state as st
        timeline_path = "MULTICHIP_timeline.json"
        trace = st.train_timeline(filename=timeline_path)
        flight_deck = _flight_deck_demo()
    finally:
        ray_tpu.shutdown()
    for row in rows:
        if row["arm"] in ("dp", "two_level", "two_level_int8", "gspmd"):
            row["parity_max_delta"] = max(
                abs(a - b) for a, b in zip(row["losses"],
                                           baseline["losses"]))
    result = {
        "metric": "multichip_train_ab",
        "n_devices": 8,
        "topology": "two-slice emulated (data=2 over DCN x fsdp=4)",
        "model": dict(_AB),
        "baseline_losses": [round(x, 6) for x in baseline["losses"]],
        "rows": rows,
        "timeline": {
            "path": timeline_path,
            "spans": len(trace),
            "tracks": sorted({str(r["pid"]) for r in trace}),
        },
        "flight_deck": flight_deck,
        "caveat": ("one contended CPU socket: stage/worker overlap is "
                   "partially serialized, so pipeline/DP wall-clock "
                   "gaps understate real multi-chip behavior; the "
                   "structural wins (no per-step host turnaround for "
                   "gspmd, sharded optimizer, descriptor-only "
                   "activation channels) are measured directly"),
    }
    print(json.dumps(result))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    return result


def gspmd_parity_dryrun(steps: int = 4) -> dict:
    """The --dryrun7b acceptance gate: the GSPMD trainer (ZeRO-1, two
    emulated slices over DCN) vs the single-process baseline — loss
    parity < 1e-2 with MFU/goodput telemetry present in the train
    report. Runs at A/B scale: a 7B single-process CPU baseline would
    need ~26 GB and hours; the 7B-scale memory story is the AOT
    zero1 arm below."""
    import ray_tpu
    from ray_tpu.train import run_single_process_baseline

    spec = _ab_spec("auto", steps)
    baseline = run_single_process_baseline(spec)
    ray_tpu.init(num_cpus=8, object_store_memory=300 * 1024 * 1024)
    try:
        row = _ab_trainer_arm("gspmd", num_workers=1, steps=steps)
    finally:
        ray_tpu.shutdown()
    delta = max(abs(a - b) for a, b in zip(row["losses"],
                                           baseline["losses"]))
    rel = delta / max(1e-9, abs(baseline["losses"][-1]))
    out = {
        "metric": "gspmd_parity_dryrun",
        "losses": [round(x, 6) for x in row["losses"]],
        "baseline_losses": [round(x, 6) for x in baseline["losses"]],
        "parity_max_delta": delta,
        "parity_rel": rel,
        "mfu": row["mfu"],
        "goodput": row["goodput"],
        "steady_step_s": row["steady_step_s"],
        "ok": bool(rel < 1e-2 and row["goodput"] is not None
                   and row["mfu"] is not None),
    }
    assert out["ok"], out
    print(json.dumps(out))
    return out


def dryrun_7b_zero1(n_devices: int = 8, config=None, batch=None,
                    seq: int = 2048):
    """7B ZeRO-1 memory accounting WITHOUT allocating 7B of host RAM:
    AOT-lower the fused sharded-update step over abstract
    ShapeDtypeStruct state and read XLA's per-device accounting. The
    honest headline is argument_bytes: the optimizer moments enter the
    program sharded 1/8 per device (vs replicated AdamW's full copies);
    temp_bytes ALSO reports the flat-buffer schedule's concat cost —
    recorded, not hidden."""
    import dataclasses

    import numpy as np

    from ray_tpu.models import LlamaConfig, LlamaModel, cross_entropy_loss
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.parallel.spmd import (Zero1Hyper, Zero1State,
                                       make_zero1_train_step)

    if config is None:
        config = dataclasses.replace(LlamaConfig.llama2_7b(),
                                     param_dtype=jnp.bfloat16)
    batch = batch or n_devices
    mesh = MeshConfig(data=2, fsdp=n_devices // 2,
                      dcn_axes=("data",)).build(num_slices=2)
    model = LlamaModel(config)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    axes = ("data", "fsdp")
    hyper = Zero1Hyper(learning_rate=1e-4, clip_norm=1.0)

    from jax.sharding import NamedSharding, PartitionSpec as P
    abstract_params = jax.eval_shape(
        lambda r: _unboxed_init(model, r, tokens), jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(abstract_params))
    W = n_devices                   # update axes ("data","fsdp") = mesh
    pad_n = -(-n_params // W) * W
    opt_sharding = NamedSharding(mesh, P(axes))
    repl = NamedSharding(mesh, P())
    state = Zero1State(
        step=jax.ShapeDtypeStruct((), jnp.int32, sharding=repl),
        params=jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                           sharding=repl),
            abstract_params),
        m=jax.ShapeDtypeStruct((pad_n,), jnp.float32,
                               sharding=opt_sharding),
        v=jax.ShapeDtypeStruct((pad_n,), jnp.float32,
                               sharding=opt_sharding),
        apply_fn=model.apply, hyper=hyper)

    def loss_fn(params, batch_data):
        logits = model.apply({"params": params}, batch_data["tokens"])
        return cross_entropy_loss(logits[:, :-1],
                                  batch_data["tokens"][:, 1:])

    data = {"tokens": jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                           sharding=repl)}
    with mesh:
        t0 = time.perf_counter()
        step = make_zero1_train_step(loss_fn, mesh, state, axes=axes,
                                     donate=False)
        compiled = step.lower(state, data).compile()
        compile_s = time.perf_counter() - t0
        mem = compiled.memory_analysis()
    opt_bytes_per_dev = 2 * pad_n * 4 // W
    print(json.dumps({
        "metric": "llama7b_zero1_dryrun",
        "model_params": n_params,
        "mesh": {"data": 2, "fsdp": n_devices // 2, "dcn": ["data"]},
        "optimizer_bytes_per_device_sharded": opt_bytes_per_dev,
        "optimizer_bytes_per_device_replicated": 2 * pad_n * 4,
        "optimizer_sharding_factor": W,
        "compile_s": round(compile_s, 1),
        "per_device_memory": {
            "argument_bytes": getattr(mem, "argument_size_in_bytes",
                                      None),
            "output_bytes": getattr(mem, "output_size_in_bytes", None),
            "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
        },
        "backend": jax.default_backend(),
    }))


def _unboxed_init(model, rng, tokens):
    from ray_tpu.parallel.mesh import unbox
    return unbox(model.init(rng, tokens)["params"])


def serve_bench(out_path: str = "BENCH_serve_r01.json") -> dict:
    """LLM serving headline (`bench.py --serve`): the in-process
    continuous-batching vs RTPU_NO_CONT_BATCH legacy engine A/B plus
    the radix shared-prefix arm — req/s, p50/p95 TTFT, prefill FLOPs
    saved — recorded as a BENCH_serve JSON artifact. Also runs the
    request-lifecycle tracing on/off A/B (same seed, same weights):
    reqtrace overhead must stay within machine noise."""
    from ray_tpu.perf_workloads import (reqtrace_overhead_ab,
                                        serve_engine_ab)

    ab = serve_engine_ab()
    rab = reqtrace_overhead_ab()
    result = {
        "metric": "llm_serve_engine_ab",
        "backend": jax.default_backend(),
        "requests": ab["continuous"]["requests"],
        "continuous": {k: ab["continuous"][k] for k in
                       ("requests_per_s", "decode_tokens_per_s",
                        "ttft_p50_s", "ttft_p95_s", "prefill_tokens",
                        "preemptions", "leaked_pages")},
        "legacy": {k: ab["legacy"][k] for k in
                   ("requests_per_s", "decode_tokens_per_s",
                    "ttft_p50_s", "ttft_p95_s", "prefill_tokens",
                    "preemptions", "leaked_pages")},
        "radix_shared_prefix": {
            k: ab["radix_shared_prefix"][k] for k in
            ("prefill_tokens", "prompt_tokens_submitted",
             "prefill_tokens_saved_frac", "shared_prefix_hits")},
        "reqtrace_ab": {
            "on": {k: rab["reqtrace_on"][k] for k in
                   ("requests_per_s", "decode_tokens_per_s",
                    "ttft_p50_s", "ttft_p95_s")},
            "off": {k: rab["reqtrace_off"][k] for k in
                    ("requests_per_s", "decode_tokens_per_s",
                     "ttft_p50_s", "ttft_p95_s")},
            "gates": rab["gates"],
        },
        "gates": ab["gates"],
        "passed": ab["passed"] and rab["passed"],
    }
    print(json.dumps(result))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    return result


def rpc_transport_bench(out_path: str = "BENCH_rpc_r01.json") -> dict:
    """Transport-observatory overhead (`bench.py --rpc`): real-socket
    loopback echo with instrumentation on vs the RTPU_NO_RPC_METRICS
    kill switch, interleaved on/off rounds (min-of-runs each side), as
    a BENCH_rpc JSON artifact. The gate is deliberately loose (50%):
    the loopback echo is the worst case — ~100us baseline against a
    fixed per-call instrumentation cost of a few us — and run-to-run
    noise on a shared box swings the ratio by tens of percent."""
    from ray_tpu.perf import rpc_bench

    out = rpc_bench(n=2000)
    overhead = out["rpc_metrics_overhead_pct"]
    gates = {"rpc_metrics_overhead_pct_lt_50": overhead < 50.0}
    result = {
        "metric": "rpc_transport_overhead_ab",
        "rpc_call_us": round(out["rpc_call_us"], 2),
        "rpc_call_nometrics_us": round(out["rpc_call_nometrics_us"], 2),
        "rpc_metrics_overhead_pct": round(overhead, 2),
        "ring_stats_read_ns": round(out["ring_stats_read_ns"], 1)
        if "ring_stats_read_ns" in out else None,
        "gates": gates,
        "passed": all(gates.values()),
    }
    print(json.dumps(result))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    return result


if __name__ == "__main__":
    import sys
    if "--dryrun7b" in sys.argv:
        if _ensure_virtual_devices(8):
            dryrun_7b(run_step="--no-step" not in sys.argv)
            dryrun_7b_zero1()
            gspmd_parity_dryrun()
    elif "--multichip" in sys.argv:
        multichip_ab(out_path="MULTICHIP_r06.json")
    elif "--serve" in sys.argv:
        serve_bench()
    elif "--rpc" in sys.argv:
        rpc_transport_bench()
    else:
        main()
