"""ray_tpu: a TPU-native distributed AI framework.

The task/actor/object core of the reference (Ray) re-designed TPU-first:
SPMD programs over device meshes are the hot path, TPU slices / ICI domains
are first-class scheduler resources, collectives lower to XLA over ICI, and
every parallelism strategy (DP/TP/PP/EP/SP/CP, ring attention, Ulysses) is a
native mesh-axis library feature.

Public API mirrors the reference's surface so users can switch:

    import ray_tpu as ray
    ray.init()

    @ray.remote
    def f(x): return x * 2

    ray.get(f.remote(21))  # 42
"""

from typing import Any, Optional

import os as _os

if _os.environ.get("RTPU_SANITIZE"):
    # Lock-order sanitizer must patch threading.Lock/RLock BEFORE the
    # runtime modules below create their module-level locks. Raylet and
    # worker mains call this themselves; this covers plain drivers.
    from ._internal.lint import sanitizer as _sanitizer
    _sanitizer.enable_from_env()

from ._internal.api import (available_resources, cancel, cluster_resources,
                            get, get_runtime_context, init, is_initialized,
                            kill, nodes, put, shutdown, wait)
from ._internal.errors import (ActorDiedError, ActorError,
                               ActorUnavailableError, GetTimeoutError,
                               ObjectLostError, OutOfMemoryError, RayTpuError,
                               RpcError, TaskCancelledError, TaskError,
                               WorkerCrashedError)
from ._internal.object_ref import ObjectRef
from .actor import ActorClass, ActorHandle, get_actor, method
from .remote_function import RemoteFunction

__version__ = "0.1.0"


def remote(*args, **kwargs):
    """`@remote` decorator for functions (tasks) and classes (actors),
    optionally with options: `@remote(num_cpus=2, num_tpus=4)`."""
    import inspect

    def make(target):
        if inspect.isclass(target):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return make(args[0])
    if args:
        raise TypeError("@remote takes only keyword options")
    return make


# Submodules re-exported lazily to keep import light.
def __getattr__(name):
    import importlib
    if name in ("util", "train", "data", "serve", "tune", "rllib",
                "accelerators", "parallel", "ops", "models", "collective",
                "cluster_utils", "experimental", "autoscaler"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "nodes", "cluster_resources",
    "available_resources", "get_runtime_context", "ObjectRef",
    "ActorClass", "ActorHandle", "RemoteFunction",
    "RayTpuError", "TaskError", "ActorError", "ActorDiedError",
    "ActorUnavailableError", "ObjectLostError", "GetTimeoutError",
    "WorkerCrashedError", "OutOfMemoryError", "RpcError",
    "TaskCancelledError",
]
