"""Accelerator observability plane: the device leg of the
observability quartet (PR 1 time, PR 3 memory, PR 5 CPU, this module
the accelerator itself).

Three concerns, one per-process module:

- **Device snapshots** — per-local-device HBM accounting via
  ``device.memory_stats()`` (TPU/GPU backends), with a
  ``live_buffers``-equivalent fallback that sums the addressable shard
  bytes of every live ``jax.Array`` per device — so the CPU backend
  (where ``memory_stats()`` is ``None``) reports real numbers and the
  whole plane is testable without hardware. Peak bytes are tracked as a
  process-lifetime watermark when the backend doesn't report one.

- **XLA compile tracking** — ``jax.monitoring`` listeners accumulate
  compile counts, cumulative compile seconds (all ``/jax/core/compile``
  phases), a per-function histogram (attributed to the nearest
  non-JAX caller frame, the PR-3 callsite idiom — compiles are rare and
  slow, a stack walk is noise), and compilation-cache hit/miss
  counters. Surfaced as ``rtpu_xla_compile_seconds_total`` /
  ``rtpu_xla_compiles_total`` / ``rtpu_xla_cache_{hits,misses}_total``.

- **Step telemetry** — :class:`StepTimer` / :func:`report_step` emit
  step-time histograms, tokens/s, an achieved-FLOP/s → MFU gauge
  (denominator from the shared ``accelerators.flops`` table), and
  goodput accounting that splits wall time into compile /
  device-compute / host-blocked buckets
  (``rtpu_goodput_seconds_total{bucket=...}``). Wired into the train
  controller's report fold, the paged-engine decode tick, and bench.py.

JAX is never imported by this module at module scope, and snapshot /
install paths only touch JAX when the process has ALREADY imported it
(``"jax" in sys.modules``) unless the caller forces it — initializing
JAX from an observability sweep would grab the host's TPU chip lock
(see accelerators/tpu.py). ``force_jax=True`` is reserved for the
process the user is driving (cli devices / accel_summary caller).

Kill switch: ``RTPU_NO_ACCEL_METRICS=1`` — zero listeners installed,
snapshots return empty, StepTimer/report_step become no-ops.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from .config import CONFIG

logger = logging.getLogger(__name__)

_JAX_COMPILE_PREFIX = "/jax/core/compile"
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_COMPILE_BOUNDARIES = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                       10.0, 30.0, 60.0, 300.0]
_STEP_BOUNDARIES = [0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 60.0]


def accel_disabled() -> bool:
    return bool(CONFIG.no_accel_metrics)


# getpid() is a real syscall on every call and this container class
# (sandboxed kernels) makes syscalls ~100x pricier than a dict lookup —
# cache the tag string once per process (modules import post-spawn, so
# the cache can't leak across processes).
_pid_cache: List[Optional[str]] = [None]


def _pid() -> str:
    pid = _pid_cache[0]
    if pid is None:
        pid = _pid_cache[0] = str(os.getpid())
    return pid


# ---------------------------------------------------------------------------
# metric series (L004: one LazyMetrics factory, literal names)
# ---------------------------------------------------------------------------


def _build_accel_metrics():
    from types import SimpleNamespace

    from ..util.metrics import Counter, Gauge, Histogram
    return SimpleNamespace(
        # gauges carry pid+device: per-process series, last-write-wins
        # per tag tuple on the cross-process merge (see runtime_metrics)
        hbm_used=Gauge(
            "rtpu_accel_hbm_used_bytes",
            "HBM bytes in use on one local device (memory_stats, "
            "or live-buffer sum on backends without it)",
            tag_keys=("pid", "device")),
        hbm_peak=Gauge(
            "rtpu_accel_hbm_peak_bytes",
            "Peak HBM bytes on one local device (backend-reported, "
            "or a process-lifetime snapshot watermark)",
            tag_keys=("pid", "device")),
        hbm_limit=Gauge(
            "rtpu_accel_hbm_limit_bytes",
            "HBM capacity of one local device (0 when the backend "
            "does not report a limit)",
            tag_keys=("pid", "device")),
        compiles=Counter(
            "rtpu_xla_compiles_total",
            "XLA backend compilations performed by this process"),
        compile_seconds=Counter(
            "rtpu_xla_compile_seconds_total",
            "Cumulative seconds spent in jax trace/lower/backend "
            "compile phases"),
        compile_hist=Histogram(
            "rtpu_xla_compile_seconds",
            "Per-compilation backend_compile duration",
            boundaries=_COMPILE_BOUNDARIES),
        cache_hits=Counter(
            "rtpu_xla_cache_hits_total",
            "XLA compilation-cache hits observed via jax.monitoring"),
        cache_misses=Counter(
            "rtpu_xla_cache_misses_total",
            "XLA compilation-cache misses observed via jax.monitoring"),
        step_time=Histogram(
            "rtpu_step_time_seconds",
            "Wall time of one accelerator step (train step / decode "
            "tick / bench step)",
            boundaries=_STEP_BOUNDARIES,
            tag_keys=("kind",)),
        step_tokens=Counter(
            "rtpu_step_tokens_total",
            "Tokens processed by reported steps",
            tag_keys=("kind",)),
        tokens_per_sec=Gauge(
            "rtpu_step_tokens_per_sec",
            "Smoothed tokens/s of reported steps (EWMA)",
            tag_keys=("pid", "kind")),
        mfu=Gauge(
            "rtpu_step_mfu",
            "Achieved-FLOP/s / peak-FLOP/s of reported steps "
            "(denominator: accelerators.flops.PEAK_FLOPS)",
            tag_keys=("pid", "kind")),
        goodput=Counter(
            "rtpu_goodput_seconds_total",
            "Reported step wall time split into compile / "
            "device-compute / comm (host-plane collectives) / "
            "host-blocked buckets",
            tag_keys=("kind", "bucket")),
    )


from ..util.metrics import LazyMetrics  # noqa: E402 — after _build def

accel_metrics = LazyMetrics(_build_accel_metrics)


# ---------------------------------------------------------------------------
# XLA compile tracking (jax.monitoring listeners)
# ---------------------------------------------------------------------------


class _CompileTracker:
    """Accumulates jax.monitoring compile/cache events. One per process;
    listeners fire synchronously on whatever thread compiles, so all
    mutation happens under one uncontended lock (compiles are rare)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.installed = False
        self.compiles = 0
        self.compile_seconds = 0.0
        # backend_compile only: these spans are disjoint wall time
        # (trace/lower events NEST under outer traces, so their sum can
        # exceed the wall clock of an enclosing region — fine for a
        # cumulative counter, wrong for a goodput split)
        self.backend_seconds = 0.0
        self.cache_hits = 0
        self.cache_misses = 0
        # event name -> count (every /jax/ event, for the raw view)
        self.events: Dict[str, int] = {}
        # attribution -> {count, seconds} (backend compiles only)
        self.per_function: Dict[str, Dict[str, float]] = {}

    def summary(self) -> Dict[str, Any]:
        with self.lock:
            per_fn = sorted(
                ({"function": k, **v} for k, v in self.per_function.items()),
                key=lambda r: -r["seconds"])
            return {
                "installed": self.installed,
                "compiles": self.compiles,
                "compile_seconds": round(self.compile_seconds, 6),
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "events": dict(self.events),
                "per_function": per_fn[:50],
            }


_TRACKER = _CompileTracker()


def _attribute_compile() -> str:
    """Nearest caller frame outside jax/jaxlib/this module: the
    user-facing name a compile bills to (cheap relative to the compile
    itself — same tradeoff as the PR-3 put()/submit callsite capture)."""
    try:
        f = sys._getframe(2)
        while f is not None:
            fn = f.f_code.co_filename
            if ("/jax/" not in fn and "/jaxlib/" not in fn
                    and not fn.endswith("_internal/accel.py")
                    and not fn.endswith("contextlib.py")
                    and "importlib" not in fn):
                return (f"{f.f_code.co_name} "
                        f"({os.path.basename(fn)}:{f.f_lineno})")
            f = f.f_back
    except Exception:  # noqa: BLE001 — attribution is best-effort
        logger.debug("compile attribution walk failed", exc_info=True)
    return "<unknown>"


def _on_duration_event(event: str, duration_s: float, **_kw):
    # A raise here would propagate into jax's monitoring dispatch MID
    # COMPILE — the listener must never break user code.
    try:
        if not event.startswith(_JAX_COMPILE_PREFIX):
            return
        metrics = accel_metrics()
        metrics.compile_seconds.inc(float(duration_s))
        tracker = _TRACKER
        if event == _BACKEND_COMPILE_EVENT:
            site = _attribute_compile()
            metrics.compiles.inc()
            metrics.compile_hist.observe(float(duration_s))
            with tracker.lock:
                tracker.compiles += 1
                tracker.compile_seconds += float(duration_s)
                tracker.backend_seconds += float(duration_s)
                tracker.events[event] = tracker.events.get(event, 0) + 1
                agg = tracker.per_function.setdefault(
                    site, {"count": 0, "seconds": 0.0})
                agg["count"] += 1
                agg["seconds"] += float(duration_s)
        else:
            with tracker.lock:
                tracker.compile_seconds += float(duration_s)
                tracker.events[event] = tracker.events.get(event, 0) + 1
    except Exception:  # noqa: BLE001 — observability must not raise
        logger.debug("compile duration listener failed", exc_info=True)


def _on_event(event: str, **_kw):
    try:
        tracker = _TRACKER
        hit = "cache_hit" in event
        miss = "cache_miss" in event
        with tracker.lock:
            tracker.events[event] = tracker.events.get(event, 0) + 1
            if hit:
                tracker.cache_hits += 1
            elif miss:
                tracker.cache_misses += 1
        if hit:
            accel_metrics().cache_hits.inc()
        elif miss:
            accel_metrics().cache_misses.inc()
    except Exception:  # noqa: BLE001 — observability must not raise
        logger.debug("compile event listener failed", exc_info=True)


def ensure_installed() -> bool:
    """Install the jax.monitoring listeners once per process. Returns
    False — and installs NOTHING — under the kill switch or when jax
    isn't importable. Idempotent and cheap once installed."""
    if accel_disabled():
        return False
    tracker = _TRACKER
    if tracker.installed:
        return True
    # Import OUTSIDE tracker.lock: the post-import hook runs
    # ensure_installed while HOLDING jax's module import lock, so a
    # concurrent caller that held tracker.lock across this import
    # (blocking on that same import lock) would deadlock the pair.
    try:
        from jax import monitoring
    except Exception:  # noqa: BLE001 — jax genuinely unavailable
        logger.debug("jax.monitoring unavailable", exc_info=True)
        return False
    with tracker.lock:
        if tracker.installed:
            return True
        monitoring.register_event_duration_secs_listener(
            _on_duration_event)
        monitoring.register_event_listener(_on_event)
        tracker.installed = True
    return True


def maybe_install() -> bool:
    """Task-boundary fast path: arm the listeners iff jax is already
    imported in this process. Two dict probes when already installed
    (or jax absent) — cheap enough for the executor's per-task call."""
    if _TRACKER.installed:
        return True
    if "jax" not in sys.modules:
        return False
    return ensure_installed()


class _JaxPostImportHook:
    """Meta-path watcher that arms the compile listeners the moment
    ``import jax`` COMPLETES anywhere in this process — the only way to
    count a process's FIRST compile, which usually happens inside the
    first task body, before any accel entry point runs. Inert for every
    other import (one string compare), removes itself after firing."""

    def find_spec(self, fullname, path=None, target=None):
        if fullname != "jax" or _TRACKER.installed:
            return None
        import importlib.machinery  # noqa: F401 — finders below need it
        for finder in sys.meta_path:
            if finder is self or not hasattr(finder, "find_spec"):
                continue
            spec = finder.find_spec(fullname, path, target)
            if spec is None or spec.loader is None:
                continue
            orig_exec = spec.loader.exec_module

            def exec_module(module, _orig=orig_exec):
                _orig(module)
                # jax/__init__ has fully executed; sys.modules["jax"]
                # is set, so registering listeners is safe now.
                try:
                    ensure_installed()
                except Exception:  # noqa: BLE001 — import must win
                    logger.debug("post-import accel install failed",
                                 exc_info=True)
                try:
                    sys.meta_path.remove(_IMPORT_HOOK)
                except ValueError:
                    pass

            spec.loader.exec_module = exec_module
            return spec
        return None


_IMPORT_HOOK = _JaxPostImportHook()


def install_import_hook() -> bool:
    """Called once at process boot (CoreWorker/raylet/GCS init). If jax
    is already imported, installs directly; otherwise registers the
    post-import watcher. Under the kill switch NOTHING is registered —
    not even the (inert) finder."""
    if accel_disabled():
        return False
    if maybe_install():
        return True
    if _IMPORT_HOOK not in sys.meta_path:
        # FRONT of meta_path: PathFinder would otherwise resolve jax
        # before this finder is ever consulted (find_spec delegates to
        # the rest of the chain, so ordering costs nothing).
        sys.meta_path.insert(0, _IMPORT_HOOK)
    return True


def uninstall() -> None:
    """Best-effort listener removal (tests; the unregister API is
    private to jax so failures just leave idle listeners behind)."""
    try:
        from jax._src import monitoring as _m  # import OUTSIDE the lock
    except Exception:  # noqa: BLE001 — private API may move
        logger.debug("jax._src.monitoring unavailable", exc_info=True)
        _m = None
    tracker = _TRACKER
    with tracker.lock:
        if not tracker.installed:
            return
        if _m is not None:
            try:
                _m._unregister_event_duration_listener_by_callback(
                    _on_duration_event)
                _m._unregister_event_listener_by_callback(_on_event)
            except Exception:  # noqa: BLE001 — private API may move
                logger.debug("jax.monitoring unregister failed",
                             exc_info=True)
        tracker.installed = False


def compile_seconds_total() -> float:
    with _TRACKER.lock:
        return _TRACKER.compile_seconds


def backend_compile_seconds_total() -> float:
    """Disjoint backend-compile wall seconds — what StepTimer's goodput
    split subtracts (see _CompileTracker.backend_seconds)."""
    with _TRACKER.lock:
        return _TRACKER.backend_seconds


def compile_summary() -> Dict[str, Any]:
    return _TRACKER.summary()


# ---------------------------------------------------------------------------
# device snapshots
# ---------------------------------------------------------------------------

# device id -> peak bytes watermark, for backends whose memory_stats()
# is None (CPU) or lacks peak_bytes_in_use.
_hbm_peak_seen: Dict[int, int] = {}
_PEAK_LOCK = threading.Lock()


def _live_buffer_bytes_by_device() -> Dict[int, int]:
    """live_buffers()-equivalent: sum every live jax.Array's addressable
    shard bytes per device. Exact for committed arrays; the fallback
    that makes the CPU backend report real HBM numbers."""
    import jax

    per_dev: Dict[int, int] = {}
    for arr in jax.live_arrays():
        try:
            for shard in arr.addressable_shards:
                dev_id = shard.device.id
                per_dev[dev_id] = per_dev.get(dev_id, 0) + \
                    int(shard.data.nbytes)
        except Exception:  # noqa: BLE001 — arrays can be deleted mid-walk
            logger.debug("live-array walk skipped one array",
                         exc_info=True)
    return per_dev


def snapshot_devices(force_jax: bool = False) -> List[Dict[str, Any]]:
    """One row per local device: identity, HBM used/peak/limit, and the
    peak-FLOPs denominator. Empty when disabled, or when jax was never
    imported here (initializing jax from an observability sweep would
    grab the TPU chip lock) unless ``force_jax``."""
    if accel_disabled():
        return []
    if not force_jax and "jax" not in sys.modules:
        return []
    import jax

    from ..accelerators.flops import peak_flops

    ensure_installed()
    rows: List[Dict[str, Any]] = []
    live = None  # computed once, only if some device lacks memory_stats
    for dev in jax.local_devices():
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — backend-dependent API
            logger.debug("memory_stats failed on %s", dev, exc_info=True)
        if stats:
            used = int(stats.get("bytes_in_use", 0))
            peak = int(stats.get("peak_bytes_in_use", 0))
            limit = int(stats.get("bytes_limit", 0))
            source = "memory_stats"
        else:
            if live is None:
                live = _live_buffer_bytes_by_device()
            used = live.get(dev.id, 0)
            peak = 0
            limit = 0
            source = "live_buffers"
        with _PEAK_LOCK:
            watermark = max(_hbm_peak_seen.get(dev.id, 0), used, peak)
            _hbm_peak_seen[dev.id] = watermark
        rows.append({
            "index": dev.id,
            "process_index": dev.process_index,
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "hbm_used_bytes": used,
            "hbm_peak_bytes": watermark,
            "hbm_limit_bytes": limit,
            "source": source,
            "peak_flops": peak_flops(dev),
        })
    metrics = accel_metrics()
    pid = _pid()
    for row in rows:
        tags = {"pid": pid, "device": str(row["index"])}
        metrics.hbm_used.set(row["hbm_used_bytes"], tags=tags)
        metrics.hbm_peak.set(row["hbm_peak_bytes"], tags=tags)
        metrics.hbm_limit.set(row["hbm_limit_bytes"], tags=tags)
    return rows


# Rate limit: one DEVICE_MEMORY_PRESSURE event per device per interval.
_pressure_last_emit: Dict[Any, float] = {}
_PRESSURE_LOCK = threading.Lock()


def check_pressure(rows: List[Dict[str, Any]],
                   watermark: Optional[float] = None
                   ) -> List[Dict[str, Any]]:
    """Device rows above the HBM watermark, rate-limited per device —
    the caller emits these as DEVICE_MEMORY_PRESSURE events into the
    GCS event log (the emission path differs by thread context)."""
    if watermark is None:
        watermark = CONFIG.accel_hbm_watermark
    out = []
    now = time.monotonic()
    for row in rows:
        limit = row.get("hbm_limit_bytes") or 0
        if limit <= 0:
            continue
        ratio = row["hbm_used_bytes"] / limit
        if ratio < watermark:
            continue
        key = row["index"]
        with _PRESSURE_LOCK:
            last = _pressure_last_emit.get(key, 0.0)
            if now - last < CONFIG.accel_pressure_min_interval_s:
                continue
            _pressure_last_emit[key] = now
        out.append({
            "device": row["index"],
            "device_kind": row["device_kind"],
            "hbm_used_bytes": row["hbm_used_bytes"],
            "hbm_limit_bytes": limit,
            "used_ratio": round(ratio, 4),
        })
    return out


def emit_pressure_event(message: str, fields: Optional[Dict[str, Any]]
                        = None) -> bool:
    """Best-effort DEVICE_MEMORY_PRESSURE publish from a USER thread
    (sync GCS bridge — never call from an io loop; async handlers
    schedule ``gcs.call("add_event", ...)`` themselves)."""
    try:
        from .core_worker import try_get_core_worker
        worker = try_get_core_worker()
        if worker is None:
            return False
        worker.gcs.call_sync(
            "add_event", event_type="DEVICE_MEMORY_PRESSURE",
            message=message, severity="WARNING",
            fields=dict(fields or {}, pid=os.getpid()), timeout=5)
        return True
    except Exception:  # noqa: BLE001 — observability is best-effort
        logger.debug("DEVICE_MEMORY_PRESSURE emit failed", exc_info=True)
        return False


# ---------------------------------------------------------------------------
# step telemetry (StepTimer / report_step) + goodput accounting
# ---------------------------------------------------------------------------

# kind -> fold of every reported step in this process
_step_stats: Dict[str, Dict[str, float]] = {}
_STEP_LOCK = threading.Lock()
_EWMA_ALPHA = 0.2

# kind -> the 6 tag dicts report_step passes to metric ops, built once
# (report_step rides the decode tick — per-call dict builds showed up)
_step_tag_cache: Dict[str, Dict[str, Dict[str, str]]] = {}


def _step_tags(kind: str) -> Dict[str, Dict[str, str]]:
    tags = _step_tag_cache.get(kind)
    if tags is None:
        pid = _pid()
        tags = _step_tag_cache[kind] = {
            "kind": {"kind": kind},
            "compile": {"kind": kind, "bucket": "compile"},
            "device": {"kind": kind, "bucket": "device"},
            "comm": {"kind": kind, "bucket": "comm"},
            "host": {"kind": kind, "bucket": "host"},
            "pid_kind": {"pid": pid, "kind": kind},
        }
    return tags


_device_kind_cache: List[Optional[str]] = [None]


def _default_device_kind() -> str:
    """device_kind of local device 0, cached; "cpu" when jax was never
    imported (don't initialize a backend from a metrics fold)."""
    kind = _device_kind_cache[0]
    if kind is None:
        if "jax" in sys.modules:
            import jax
            try:
                kind = getattr(jax.local_devices()[0], "device_kind",
                               "cpu")
            except Exception:  # noqa: BLE001 — backend init can fail
                logger.debug("device-kind probe failed", exc_info=True)
                kind = "cpu"
        else:
            kind = "cpu"
        _device_kind_cache[0] = kind
    return kind


def report_step(kind: str, wall_s: float, tokens: int = 0,
                device_s: float = 0.0, compile_s: float = 0.0,
                flops: float = 0.0,
                device_kind: Optional[str] = None,
                steps: int = 1,
                comm_s: float = 0.0) -> Optional[Dict[str, float]]:
    """Fold one step (or ``steps`` uniform steps) into the process's
    step telemetry: step-time histogram, tokens/s EWMA gauge, MFU gauge
    (``flops`` = total FLOPs the interval performed, divided by wall
    and the shared peak table), and the compile/device/comm/host
    goodput split (``comm_s`` = host-plane collective time, so
    comm-bound and compute-bound steps are distinguishable;
    host-blocked = wall − compile − device − comm). Returns the
    derived numbers, or None when the plane is disabled."""
    if accel_disabled() or wall_s <= 0:
        return None
    metrics = accel_metrics()
    per_step = wall_s / max(1, steps)
    tags = _step_tags(kind)
    if steps == 1:
        metrics.step_time.observe(per_step, tags=tags["kind"])
    else:
        # aggregated interval: observe the mean once per reported step
        # (bounded — an interval never unrolls into thousands of
        # histogram appends)
        for _ in range(min(steps, 64)):
            metrics.step_time.observe(per_step, tags=tags["kind"])
    compile_s = max(0.0, min(compile_s, wall_s))
    device_s = max(0.0, min(device_s, wall_s - compile_s))
    comm_s = max(0.0, min(comm_s, wall_s - compile_s - device_s))
    host_s = max(0.0, wall_s - compile_s - device_s - comm_s)
    if compile_s:
        metrics.goodput.inc(compile_s, tags=tags["compile"])
    if device_s:
        metrics.goodput.inc(device_s, tags=tags["device"])
    if comm_s:
        metrics.goodput.inc(comm_s, tags=tags["comm"])
    if host_s:
        metrics.goodput.inc(host_s, tags=tags["host"])
    tokens_per_s = None
    if tokens:
        metrics.step_tokens.inc(tokens, tags=tags["kind"])
        tokens_per_s = tokens / wall_s
    mfu = None
    if flops:
        from ..accelerators.flops import peak_flops_for_kind
        peak = peak_flops_for_kind(device_kind or _default_device_kind())
        mfu = (flops / wall_s) / peak
        metrics.mfu.set(mfu, tags=tags["pid_kind"])
    with _STEP_LOCK:
        agg = _step_stats.setdefault(kind, {
            "steps": 0, "wall_s": 0.0, "tokens": 0,
            "compile_s": 0.0, "device_s": 0.0, "comm_s": 0.0,
            "host_s": 0.0, "tokens_per_s": 0.0, "mfu": 0.0})
        agg["steps"] += steps
        agg["wall_s"] += wall_s
        agg["tokens"] += tokens
        agg["compile_s"] += compile_s
        agg["device_s"] += device_s
        agg["comm_s"] += comm_s
        agg["host_s"] += host_s
        if tokens_per_s is not None:
            prev = agg["tokens_per_s"]
            agg["tokens_per_s"] = tokens_per_s if not prev else \
                prev + _EWMA_ALPHA * (tokens_per_s - prev)
            metrics.tokens_per_sec.set(
                agg["tokens_per_s"], tags=tags["pid_kind"])
        if mfu is not None:
            agg["mfu"] = mfu
    return {"wall_s": wall_s, "tokens_per_s": tokens_per_s or 0.0,
            "mfu": mfu or 0.0, "compile_s": compile_s,
            "device_s": device_s, "comm_s": comm_s, "host_s": host_s}


def step_summary() -> List[Dict[str, Any]]:
    """Per-kind fold of every step this process reported."""
    with _STEP_LOCK:
        out = []
        for kind, agg in _step_stats.items():
            row = dict(agg, kind=kind)
            steps = max(1, int(agg["steps"]))
            row["mean_step_s"] = agg["wall_s"] / steps
            out.append(row)
    out.sort(key=lambda r: -r["wall_s"])
    return out


class StepAccumulator:
    """Amortizes report_step over hot loops: each step folds into a
    handful of float adds, and one aggregated ``report_step(steps=n)``
    fires every ``every`` steps — so a millisecond-scale decode tick
    pays ~a perf_counter pair, not six metric-series ops. The histogram
    sees mean-of-window observations (acceptable smoothing for a
    window of 16 uniform ticks); gauges/counters are exact."""

    __slots__ = ("kind", "every", "device_kind",
                 "_n", "_wall", "_tokens", "_device", "_compile",
                 "_comm", "_flops")

    def __init__(self, kind: str, every: int = 16,
                 device_kind: Optional[str] = None):
        self.kind = kind
        self.every = max(1, int(every))
        self.device_kind = device_kind
        self._n = 0
        self._wall = self._device = self._compile = 0.0
        self._comm = self._flops = 0.0
        self._tokens = 0

    def add(self, wall_s: float, tokens: int = 0, device_s: float = 0.0,
            compile_s: float = 0.0, flops: float = 0.0,
            comm_s: float = 0.0):
        self._n += 1
        self._wall += wall_s
        self._tokens += tokens
        self._device += device_s
        self._compile += compile_s
        self._comm += comm_s
        self._flops += flops
        if self._n >= self.every:
            self.flush()

    def flush(self) -> Optional[Dict[str, float]]:
        n = self._n
        if not n:
            return None
        out = report_step(
            self.kind, self._wall, tokens=self._tokens,
            device_s=self._device, compile_s=self._compile,
            flops=self._flops, device_kind=self.device_kind, steps=n,
            comm_s=self._comm)
        self._n = 0
        self._wall = self._device = self._compile = 0.0
        self._comm = self._flops = 0.0
        self._tokens = 0
        return out


class StepTimer:
    """Times one step and reports it on exit.

    ::

        with StepTimer("decode", tokens=n, flops=2 * params * n) as t:
            host_side_prep()
            with t.device():
                out = jitted_step(...)   # device-compute bucket
        # exit: wall split into compile (jax.monitoring delta during the
        # step) / device (time inside t.device()) / host (the rest)

    ``sink``: a StepAccumulator to fold into instead of reporting
    immediately (hot loops — see the paged decode tick). Near-zero when
    the plane is disabled: __enter__/__exit__ degrade to two attribute
    checks and report nothing."""

    __slots__ = ("kind", "tokens", "flops", "device_kind", "enabled",
                 "device_s", "comm_s", "result", "sink", "_t0", "_c0")

    def __init__(self, kind: str, tokens: int = 0, flops: float = 0.0,
                 device_kind: Optional[str] = None,
                 sink: Optional[StepAccumulator] = None):
        self.kind = kind
        self.tokens = tokens
        self.flops = flops
        self.device_kind = device_kind
        self.sink = sink
        self.enabled = not accel_disabled()
        self.device_s = 0.0
        self.comm_s = 0.0
        self.result: Optional[Dict[str, float]] = None
        self._t0 = 0.0
        self._c0 = 0.0

    def __enter__(self) -> "StepTimer":
        if self.enabled:
            ensure_installed()
            self._c0 = backend_compile_seconds_total()
            self._t0 = time.perf_counter()
        return self

    def device(self):
        return _DeviceSpan(self)

    def comm(self):
        """``with timer.comm():`` — host-plane collective time (gradient
        allreduce, loss reduction) lands in the ``comm`` goodput bucket
        instead of being misread as host-blocked."""
        return _CommSpan(self)

    def __exit__(self, exc_type, _exc, _tb):
        if not self.enabled or exc_type is not None:
            return False
        wall = time.perf_counter() - self._t0
        compile_s = backend_compile_seconds_total() - self._c0
        if self.sink is not None:
            self.sink.add(wall, tokens=self.tokens,
                          device_s=self.device_s, compile_s=compile_s,
                          flops=self.flops, comm_s=self.comm_s)
        else:
            self.result = report_step(
                self.kind, wall, tokens=self.tokens,
                device_s=self.device_s, compile_s=compile_s,
                flops=self.flops, device_kind=self.device_kind,
                comm_s=self.comm_s)
        return False


class _DeviceSpan:
    """Accumulates time spent inside ``with timer.device():`` into the
    owning StepTimer's device-compute bucket. A span that straddles an
    XLA recompile (the first call of a freshly-traced step fn compiles
    INSIDE the span) would bill the compile seconds as device compute;
    the disjoint backend-compile window the tracker already measures is
    subtracted, so those seconds land in the compile bucket alone."""

    __slots__ = ("_timer", "_t0", "_c0")

    def __init__(self, timer: StepTimer):
        self._timer = timer
        self._t0 = 0.0
        self._c0 = 0.0

    def __enter__(self):
        self._c0 = backend_compile_seconds_total()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, _exc, _tb):
        span = time.perf_counter() - self._t0
        span -= backend_compile_seconds_total() - self._c0
        self._timer.device_s += max(0.0, span)
        return False


class _CommSpan:
    """Accumulates time spent inside ``with timer.comm():`` into the
    owning StepTimer's comm (host-plane collective) bucket."""

    __slots__ = ("_timer", "_t0")

    def __init__(self, timer: StepTimer):
        self._timer = timer
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, _exc, _tb):
        self._timer.comm_s += time.perf_counter() - self._t0
        return False


# ---------------------------------------------------------------------------
# the per-process report (get_accel_report RPC body)
# ---------------------------------------------------------------------------


def accel_report(force_jax: bool = False) -> Dict[str, Any]:
    """Everything this process knows about its accelerators: device
    rows, compile tracking, step telemetry, and any pressure rows the
    caller should publish. ``devices`` stays empty in processes that
    never imported jax (see snapshot_devices) unless ``force_jax``."""
    disabled = accel_disabled()
    report: Dict[str, Any] = {
        "pid": os.getpid(),
        "disabled": disabled,
        "jax_initialized": "jax" in sys.modules,
        "devices": [],
        "compile": compile_summary(),
        "steps": step_summary(),
        "pressure": [],
    }
    if disabled:
        return report
    devices = snapshot_devices(force_jax=force_jax)
    report["devices"] = devices
    report["jax_initialized"] = report["jax_initialized"] or force_jax
    report["pressure"] = check_pressure(devices)
    return report
