"""Sanctioned fire-and-forget task spawning (rtpulint rule A001).

``asyncio.create_task(coro())`` with the handle dropped is how
background work silently dies: an exception raised by the coroutine
sits in the garbage-collected task and surfaces — if ever — as an
"exception was never retrieved" line at loop shutdown, long after the
subsystem it killed stopped making progress. rtpulint's A001 flags
every such site; :func:`spawn` is the approved replacement. It attaches
a done-callback that retrieves the task's exception, logs it through
the structured logger with the spawn's ``what`` label, and bumps
``rtpu_async_task_errors_total`` so a dying background loop shows up on
dashboards instead of in a post-mortem.

Intentionally tiny: no retry, no supervision — a failed background task
is a bug to surface, not a condition to paper over.
"""

from __future__ import annotations

import asyncio
import functools
import logging
import threading
from types import SimpleNamespace
from typing import Optional

logger = logging.getLogger(__name__)


def _build_aio_metrics():
    from ..util.metrics import Counter
    return SimpleNamespace(
        task_errors=Counter(
            "rtpu_async_task_errors_total",
            "Exceptions raised by fire-and-forget background tasks "
            "(spawned via _internal.aio.spawn), by task label",
            tag_keys=("what",)),
    )


# util.metrics' LazyMetrics can't be imported at module scope here:
# core_worker imports this module, and ray_tpu.util's package __init__
# imports core_worker back — so even the import must be deferred to
# first use, not just the build().
_METRICS_LOCK = threading.Lock()
_METRICS_NS = None


def _METRICS():
    global _METRICS_NS
    if _METRICS_NS is None:
        with _METRICS_LOCK:
            if _METRICS_NS is None:
                _METRICS_NS = _build_aio_metrics()
    return _METRICS_NS


def _sink(what: str, task: "asyncio.Task"):
    if task.cancelled():
        return                      # orderly shutdown, not a failure
    exc = task.exception()
    if exc is None:
        return
    try:
        _METRICS().task_errors.inc(tags={"what": what})
    except Exception:  # metrics must never mask the error log below
        logger.debug("task-error metric bump failed", exc_info=True)
    logger.error("background task %r failed", what, exc_info=exc)


def spawn(coro, *, what: str = "",
          loop: Optional[asyncio.AbstractEventLoop] = None
          ) -> "asyncio.Task":
    """Schedule ``coro`` as a background task whose failures are logged
    and counted instead of silently dropped.

    ``what`` labels the task in logs and in the
    ``rtpu_async_task_errors_total`` counter (defaults to the
    coroutine's qualname). Pass ``loop`` to schedule onto a specific
    loop (``loop.create_task``); otherwise the running loop is used.
    Returns the task — callers MAY still retain it for cancellation,
    but don't have to for error visibility.
    """
    name = what or getattr(coro, "__qualname__", "") or repr(coro)
    task = loop.create_task(coro) if loop is not None \
        else asyncio.ensure_future(coro)
    task.add_done_callback(functools.partial(_sink, name))
    return task
