"""SLO alert engine: declarative rules over the metrics registry.

An :class:`AlertRule` names a metric, a rolling window, and a predicate
(``metric, window_s, predicate, severity``); the :class:`AlertEngine`
samples each rule's metric from process snapshots (counter -> sum,
gauge -> max, histogram -> p95 by default), keeps a per-rule rolling
``(ts, value)`` window, and when the predicate trips fires a bounded
GCS alert-table row (``add_alert`` -> ``SLO_ALERT`` event) — surfaced
via ``cli alerts``, ``/api/alerts``, and the dashboard Alerts tab.

Two evaluation paths share all the logic:

* ``ensure_engine()`` — a registry-registered daemon thread evaluating
  every ``alert_eval_interval_s``; the production path.
* ``engine.evaluate_once(snapshots=..., now=...)`` — one deterministic
  evaluation over caller-supplied snapshots and clock; what the tests
  and ``bench.py --multichip`` drive.

Firing is rate-limited per rule (``alert_min_interval_s``) so a
breached SLO produces a heartbeat, not an event flood. Default rules:
collective-wait p95 (the straggler SLO), HBM high-watermark, and
step-time regression vs an EWMA baseline.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .config import CONFIG

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# metric sampling (snapshots -> one scalar per rule per eval)
# ---------------------------------------------------------------------------


def _fold_metric(snapshots: List[Dict[str, Any]], name: str):
    """Merge every process's series of metric ``name`` into one value:
    counters sum, gauges max (worst process wins for SLO purposes),
    histograms merge bucket/sum/count. Returns (kind, folded) or None
    if no process has the metric yet."""
    from ..util.metrics import _iter_series
    kind = None
    acc: Any = None
    for snap in snapshots:
        if snap.get("name") != name:
            continue
        kind = snap.get("kind", "untyped")
        for _tags, value in _iter_series(snap):
            if kind == "histogram":
                if acc is None:
                    acc = {"boundaries": list(value.get("boundaries", [])),
                           "buckets": list(value.get("buckets", [])),
                           "sum": float(value.get("sum", 0.0)),
                           "count": int(value.get("count", 0))}
                elif acc["boundaries"] == value.get("boundaries"):
                    acc["buckets"] = [a + b for a, b in
                                      zip(acc["buckets"], value["buckets"])]
                    acc["sum"] += float(value.get("sum", 0.0))
                    acc["count"] += int(value.get("count", 0))
            elif kind == "counter":
                acc = (acc or 0.0) + float(value)
            else:  # gauge/untyped
                acc = float(value) if acc is None else max(acc, float(value))
    if kind is None or acc is None:
        return None
    return kind, acc


def _hist_quantile(state: Dict[str, Any], q: float) -> Optional[float]:
    """Upper-bound quantile estimate from merged histogram buckets: the
    smallest boundary whose cumulative count covers q of observations
    (the overflow bucket reports the last finite boundary — a floor,
    but a breach at that resolution already breached any finite SLO)."""
    count = int(state.get("count", 0))
    if count <= 0:
        return None
    target = q * count
    cum = 0
    boundaries = state.get("boundaries", [])
    for i, n in enumerate(state.get("buckets", [])):
        cum += n
        if cum >= target:
            return float(boundaries[i]) if i < len(boundaries) \
                else float(boundaries[-1]) if boundaries else None
    return float(boundaries[-1]) if boundaries else None


def sample_metric(snapshots: List[Dict[str, Any]], name: str,
                  reduce: str = "auto") -> Optional[float]:
    """One scalar sample of metric ``name`` from snapshots. ``reduce``:
    ``sum`` / ``max`` / ``mean`` / ``p95`` / ``p99``, or ``auto`` (by
    kind: counter -> sum, gauge -> max, histogram -> p95)."""
    folded = _fold_metric(snapshots, name)
    if folded is None:
        return None
    kind, acc = folded
    if kind == "histogram":
        if reduce == "mean":
            return acc["sum"] / acc["count"] if acc["count"] else None
        if reduce == "p99":
            return _hist_quantile(acc, 0.99)
        return _hist_quantile(acc, 0.95)
    return float(acc)


class DeltaMean:
    """Stateful ``value_fn``: the mean of a histogram's NEW observations
    since the previous evaluation (cumulative sum/count deltas), so a
    recent regression isn't diluted by the all-time average. Returns
    None on evals with no new observations — the rule skips them."""

    def __init__(self, metric: str):
        self.metric = metric
        self._last: Tuple[float, int] = (0.0, 0)

    def __call__(self, snapshots: List[Dict[str, Any]]) -> Optional[float]:
        folded = _fold_metric(snapshots, self.metric)
        if folded is None or folded[0] != "histogram":
            return None
        acc = folded[1]
        last_sum, last_count = self._last
        d_sum = acc["sum"] - last_sum
        d_count = acc["count"] - last_count
        if d_count <= 0:
            return None
        self._last = (acc["sum"], acc["count"])
        return d_sum / d_count


class EwmaRegression:
    """Stateful predicate: fires when the sample exceeds ``multiple`` x
    the EWMA of PRIOR samples (the baseline excludes the sample under
    test, so a sustained regression keeps firing until the baseline
    catches up). Warmup: never fires before ``min_samples`` priors."""

    def __init__(self, multiple: float = 1.5, alpha: float = 0.3,
                 min_samples: int = 3):
        self.multiple = float(multiple)
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self._ewma: Optional[float] = None
        self._n = 0

    def __call__(self, value: float, window: List[float]) -> bool:
        prior, n = self._ewma, self._n
        self._n += 1
        self._ewma = value if prior is None else \
            self.alpha * value + (1.0 - self.alpha) * prior
        return (prior is not None and n >= self.min_samples
                and value > self.multiple * prior)


# ---------------------------------------------------------------------------
# rules + engine
# ---------------------------------------------------------------------------


class AlertRule:
    """One declarative SLO: sample ``metric`` (or ``value_fn``), keep a
    ``window_s`` rolling window, fire at ``severity`` when
    ``predicate(value, window_values)`` is true. ``predicate`` may be
    stateful (e.g. :class:`EwmaRegression`); ``message`` is a callable
    ``value -> str`` or None for the default."""

    def __init__(self, name: str, metric: Optional[str] = None, *,
                 window_s: float = 60.0,
                 predicate: Callable[[float, List[float]], bool],
                 severity: str = "WARNING",
                 reduce: str = "auto",
                 value_fn: Optional[Callable[[List[Dict[str, Any]]],
                                             Optional[float]]] = None,
                 message: Optional[Callable[[float], str]] = None,
                 min_interval_s: Optional[float] = None):
        if metric is None and value_fn is None:
            raise ValueError(f"rule {name!r} needs metric= or value_fn=")
        self.name = name
        self.metric = metric
        self.window_s = float(window_s)
        self.predicate = predicate
        self.severity = severity
        self.reduce = reduce
        self.value_fn = value_fn
        self.message = message
        self.min_interval_s = min_interval_s

    def sample(self, snapshots: List[Dict[str, Any]]) -> Optional[float]:
        if self.value_fn is not None:
            return self.value_fn(snapshots)
        return sample_metric(snapshots, self.metric, self.reduce)

    def render(self, value: float) -> str:
        if self.message is not None:
            return self.message(value)
        return (f"{self.name}: value {value:.6g} breached SLO over "
                f"{self.window_s:.0f}s window"
                + (f" (metric {self.metric})" if self.metric else ""))


def _hbm_watermark_ratio(snapshots: List[Dict[str, Any]]
                         ) -> Optional[float]:
    """used/limit across the worst accelerator process — the HBM
    high-watermark SLO's sample."""
    used = sample_metric(snapshots, "rtpu_accel_hbm_used_bytes", "max")
    limit = sample_metric(snapshots, "rtpu_accel_hbm_limit_bytes", "max")
    if used is None or not limit:
        return None
    return used / limit


def default_rules() -> List[AlertRule]:
    """The stock SLOs — train plane (collective wait, HBM watermark,
    step-time regression) and serve plane (TTFT p95, lease-queue age,
    KV-page occupancy; thresholds from the RTPU_SERVE_*_SLO flags).
    One engine covers both planes."""
    return [
        AlertRule(
            "collective_wait_p95",
            metric="rtpu_collective_wait_seconds",
            window_s=60.0, reduce="p95",
            predicate=lambda v, _w: v > 0.025,
            severity="WARNING",
            message=lambda v: (f"collective entry-wait p95 {v:.3f}s "
                               f"exceeds 25ms SLO — a rank is holding "
                               f"up the fabric (see cli stragglers)")),
        AlertRule(
            "hbm_watermark",
            value_fn=_hbm_watermark_ratio,
            window_s=60.0,
            predicate=lambda v, _w: v > float(CONFIG.accel_hbm_watermark),
            severity="CRITICAL",
            message=lambda v: (f"HBM use at {v:.0%} of device limit "
                               f"(watermark "
                               f"{float(CONFIG.accel_hbm_watermark):.0%})")),
        AlertRule(
            "step_time_regression",
            window_s=300.0,
            value_fn=DeltaMean("rtpu_step_time_seconds"),
            predicate=EwmaRegression(multiple=1.5),
            severity="WARNING",
            message=lambda v: (f"step time regressed to {v:.3f}s — "
                               f">1.5x the EWMA baseline")),
        AlertRule(
            "serve_ttft_p95",
            metric="rtpu_llm_ttft_seconds",
            window_s=60.0, reduce="p95",
            predicate=lambda v, _w: v > float(
                CONFIG.serve_ttft_p95_slo_s),
            severity="WARNING",
            message=lambda v: (
                f"serve TTFT p95 {v:.3f}s exceeds "
                f"{float(CONFIG.serve_ttft_p95_slo_s):.3g}s SLO — "
                f"decompose the tail with cli requests / why_slow")),
        AlertRule(
            "serve_queue_age",
            metric="rtpu_lease_queue_age_seconds",
            window_s=60.0, reduce="max",
            predicate=lambda v, _w: v > float(
                CONFIG.serve_queue_age_slo_s),
            severity="WARNING",
            message=lambda v: (
                f"lease queue age {v:.1f}s exceeds "
                f"{float(CONFIG.serve_queue_age_slo_s):.3g}s SLO — "
                f"requests are starving behind held leases")),
        AlertRule(
            "serve_kv_occupancy",
            metric="rtpu_llm_kv_page_utilization",
            window_s=60.0, reduce="max",
            predicate=lambda v, _w: v > float(
                CONFIG.serve_kv_occupancy_slo),
            severity="WARNING",
            message=lambda v: (
                f"KV page pool {v:.0%} full (SLO "
                f"{float(CONFIG.serve_kv_occupancy_slo):.0%}) — "
                f"preemption churn imminent; add replicas or pages")),
        AlertRule(
            "rpc_client_p99",
            metric="rtpu_rpc_client_seconds",
            window_s=60.0, reduce="p99",
            predicate=lambda v, _w: v > float(
                CONFIG.rpc_client_p99_slo_s),
            severity="WARNING",
            message=lambda v: (
                f"rpc client p99 {v:.3f}s exceeds "
                f"{float(CONFIG.rpc_client_p99_slo_s):.3g}s SLO — "
                f"attribute the tail with cli rpc --slow")),
        AlertRule(
            "ring_backpressure",
            metric="rtpu_ring_queue_depth",
            window_s=60.0, reduce="max",
            predicate=lambda v, _w: v > float(
                CONFIG.ring_backpressure_depth),
            severity="WARNING",
            message=lambda v: (
                f"native ring queue depth {v:.0f} exceeds "
                f"{CONFIG.ring_backpressure_depth} — a drain loop is "
                f"not keeping up (see cli rpc rings)")),
    ]


class AlertEngine:
    """Evaluates rules over metric snapshots and fires rate-limited
    alerts through the GCS alert table. ``emit`` is injectable for
    tests; the default posts ``add_alert`` over the sync GCS bridge."""

    def __init__(self, rules: Optional[List[AlertRule]] = None,
                 emit: Optional[Callable[[Dict[str, Any]], Any]] = None):
        self.rules = list(rules) if rules is not None else default_rules()
        self._emit = emit if emit is not None else _emit_alert
        self._lock = threading.Lock()
        # rule name -> deque[(ts, value)] rolling window
        self._windows: Dict[str, deque] = {}
        # rule name -> ts of last fire (rate limit)
        self._last_fire: Dict[str, float] = {}
        self.evals = 0
        self.fired: List[Dict[str, Any]] = []

    def add_rule(self, rule: AlertRule):
        with self._lock:
            self.rules.append(rule)

    def evaluate_once(self,
                      snapshots: Optional[List[Dict[str, Any]]] = None,
                      now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass. With ``snapshots``/``now`` supplied this
        is fully deterministic (the test/bench path); without, it reads
        this process's live registry and the monotonic clock."""
        if snapshots is None:
            from ..util.metrics import snapshot_all
            snapshots = snapshot_all()
        if now is None:
            now = time.monotonic()
        fired: List[Dict[str, Any]] = []
        with self._lock:
            self.evals += 1
            rules = list(self.rules)
        for rule in rules:
            try:
                value = rule.sample(snapshots)
            except Exception:  # noqa: BLE001 — one bad rule can't stall the pass
                logger.debug("alert rule %s sample failed", rule.name,
                             exc_info=True)
                continue
            if value is None:
                continue
            with self._lock:
                win = self._windows.setdefault(rule.name, deque())
                win.append((now, float(value)))
                while win and win[0][0] < now - rule.window_s:
                    win.popleft()
                values = [v for _, v in win]
            try:
                hit = bool(rule.predicate(float(value), values))
            except Exception:  # noqa: BLE001
                logger.debug("alert rule %s predicate failed", rule.name,
                             exc_info=True)
                continue
            if not hit:
                continue
            min_interval = rule.min_interval_s
            if min_interval is None:
                min_interval = float(CONFIG.alert_min_interval_s)
            with self._lock:
                last = self._last_fire.get(rule.name)
                if last is not None and now - last < min_interval:
                    continue
                self._last_fire[rule.name] = now
            row = {
                "rule": rule.name,
                "severity": rule.severity,
                "message": rule.render(float(value)),
                "value": round(float(value), 6),
                "window_s": rule.window_s,
                "metric": rule.metric or "",
            }
            with self._lock:
                self.fired.append(row)
            fired.append(row)
            try:
                self._emit(row)
            except Exception:  # noqa: BLE001 — alerting is best-effort
                logger.debug("alert emit failed for %s", rule.name,
                             exc_info=True)
        return fired

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"rules": [r.name for r in self.rules],
                    "evals": self.evals,
                    "fired": list(self.fired)}


def _emit_alert(row: Dict[str, Any]) -> bool:
    """Post one alert row into the GCS alert table from a user thread
    (same sync bridge as the straggler/pressure events)."""
    try:
        from .core_worker import try_get_core_worker
        worker = try_get_core_worker()
        if worker is None:
            return False
        worker.gcs.call_sync(
            "add_alert", rule=row["rule"], message=row["message"],
            severity=row["severity"],
            fields={"value": row["value"], "window_s": row["window_s"],
                    "metric": row["metric"]},
            timeout=5)
        return True
    except Exception:  # noqa: BLE001
        logger.debug("add_alert RPC failed", exc_info=True)
        return False


# ---------------------------------------------------------------------------
# daemon lifecycle
# ---------------------------------------------------------------------------

_engine_lock = threading.Lock()
_engine: Optional[AlertEngine] = None
_engine_thread: Optional[threading.Thread] = None
_engine_stop: Optional[threading.Event] = None


def ensure_engine(rules: Optional[List[AlertRule]] = None) -> AlertEngine:
    """Start (or return) this process's alert-engine daemon: evaluates
    every ``alert_eval_interval_s`` against the live registry. Liveness
    -keyed like the metrics flusher — after node teardown joins the
    thread, the next ensure_engine() restarts it cleanly."""
    global _engine, _engine_thread, _engine_stop
    with _engine_lock:
        if _engine is not None and _engine_thread is not None \
                and (_engine_thread.ident is None
                     or _engine_thread.is_alive()) \
                and not _engine_stop.is_set():
            return _engine
        engine = AlertEngine(rules=rules)
        stop = threading.Event()
        _engine, _engine_stop = engine, stop
        from .threads import spawn_daemon
        _engine_thread = spawn_daemon(
            _eval_loop, name="rtpu-alert-engine", args=(engine, stop),
            stop=stop.set)
        return engine


def _cluster_snapshots() -> List[Dict[str, Any]]:
    """The daemon's snapshot source: every process's flushed metrics
    from the GCS KV when a cluster is reachable (SLOs are cluster
    properties), else this process's live registry."""
    try:
        from .core_worker import try_get_core_worker
        worker = try_get_core_worker()
        if worker is not None:
            from ..util.metrics import collect_cluster_metrics
            snaps = collect_cluster_metrics(worker.gcs)
            if snaps:
                return snaps
    except Exception:  # noqa: BLE001 — fall back to the local registry
        logger.debug("cluster metric collect failed", exc_info=True)
    from ..util.metrics import snapshot_all
    return snapshot_all()


def _eval_loop(engine: AlertEngine, stop: threading.Event):
    while not stop.wait(float(CONFIG.alert_eval_interval_s)):
        try:
            engine.evaluate_once(snapshots=_cluster_snapshots())
        except Exception:  # noqa: BLE001 — the loop must survive
            logger.debug("alert evaluation pass failed", exc_info=True)


def get_engine() -> Optional[AlertEngine]:
    return _engine
