"""Top-level API (reference: python/ray/_private/worker.py — init, connect,
get/put/wait, shutdown, kill, cluster introspection)."""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from .config import CONFIG
from .core_worker import (CoreWorker, get_core_worker, set_core_worker,
                          try_get_core_worker, RUNTIME_CTX)
from .errors import RayTpuError
from .ids import JobID
from .node import Node, default_resources
from .object_ref import ObjectRef
from .rpc import Address

logger = logging.getLogger(__name__)

_init_lock = threading.Lock()
_local_node: Optional[Node] = None
_namespace: str = ""


def is_initialized() -> bool:
    return try_get_core_worker() is not None


def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         labels: Optional[Dict[str, str]] = None,
         namespace: str = "",
         object_store_memory: Optional[int] = None,
         ignore_reinit_error: bool = False,
         log_to_driver: bool = True,
         _system_config: Optional[Dict[str, Any]] = None,
         _node: Optional[Node] = None):
    """Start (or connect to) a cluster and attach this process as a driver.

    With no address, starts a head node in-process: GCS + raylet on the io
    loop, workers as subprocesses — the local-mode analog of the reference's
    `ray.init()` process bring-up (reference: _private/node.py:1340).
    """
    global _local_node, _namespace
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return get_core_worker()
            raise RuntimeError("ray_tpu.init() called twice; "
                               "pass ignore_reinit_error=True to allow")
        if _system_config:
            CONFIG.apply_system_config(_system_config)
        _namespace = namespace

        if _node is not None:
            node = _node
            gcs_address = node.gcs_address
        elif address in (None, "local"):
            node_resources = dict(resources or {})
            node_resources.update(default_resources(num_cpus, num_tpus))
            from ..accelerators import tpu as tpu_accel
            node_resources.update(tpu_accel.node_tpu_resources())
            node_labels = dict(labels or {})
            node_labels.update(tpu_accel.node_tpu_labels())
            node = Node(head=True, resources=node_resources,
                        labels=node_labels,
                        object_store_memory=object_store_memory)
            node.start()
            _local_node = node
            gcs_address = node.gcs_address
        else:
            host, port = address.rsplit(":", 1)
            gcs_address = (host, int(port))
            node = None

        if node is not None:
            raylet_address = node.raylet_address
            node_id = node.node_id
            node_index = node.node_index
            session_name = node.session_name
        else:
            # Connect to a remote cluster: attach to the head node's raylet.
            from .gcs_client import GcsClient
            probe = GcsClient(gcs_address)
            nodes = probe.call_sync("get_all_nodes")
            head = next((n for n in nodes if n.get("is_head")), nodes[0])
            raylet_address = tuple(head["address"])
            node_id = head["node_id"]
            node_index = head.get("node_index", 0)
            session_name = head.get("session_name") or "connected"

        worker = CoreWorker(
            mode="driver",
            session_name=node.session_name if node else session_name,
            gcs_address=gcs_address, raylet_address=raylet_address,
            node_id=node_id, node_index=node_index)
        worker.start()
        import uuid as _uuid
        job_id = worker.gcs.call_sync(
            "add_job", driver_address=worker.rpc_address,
            namespace=namespace,
            # Idempotency token: a retry across a GCS failover coalesces
            # onto the same job instead of double-registering.
            token=_uuid.uuid4().hex)
        worker.job_id = job_id
        try:
            # Seed the failover detector: the client must know the
            # CURRENT incarnation to tell a restart from first contact.
            info = worker.gcs.call_sync("gcs_info", timeout=10)
            worker.gcs.note_incarnation(info["incarnation"])
        except Exception:
            logger.debug("gcs_info seed fetch failed", exc_info=True)
        # Propagate the driver's import environment so workers can
        # deserialize functions defined in driver-side modules (reference:
        # runtime-env working_dir / py_modules path propagation).
        import sys as _sys
        from . import serialization as _ser
        worker.gcs.put("job_meta", job_id.hex(), _ser.dumps({
            "sys_path": [p for p in _sys.path if p],
            "cwd": os.getcwd(),
        }))
        set_core_worker(worker)
        if log_to_driver and CONFIG.log_to_driver:
            _attach_log_stream(worker)
        atexit.register(_atexit_shutdown)
        return worker


def _attach_log_stream(worker):
    """Print worker stdout/stderr streamed over GCS pubsub (reference:
    _private/log_monitor.py + worker.py print_logs)."""
    import sys

    async def _on_logs(message):
        # Per-job routing: print only this driver's workers. Fail OPEN —
        # messages with no job (worker boot output before its first
        # lease) or the nil job (workers leased by system actors before
        # they adopt a job) pass through so crash tracebacks and stack
        # dumps always surface somewhere.
        from .ids import JobID
        job = message.get("job")
        my_job = getattr(worker, "job_id", None)
        if (job is not None and my_job is not None
                and job != my_job.hex()
                and job != JobID.from_int(0).hex()):
            return
        stream = sys.stderr if message.get("stream") == "stderr" \
            else sys.stdout
        pid = message.get("pid")
        for line in message.get("lines", ()):
            print(f"(pid={pid}) {line}",  # stdout ok: log stream
                  file=stream)
        try:
            stream.flush()
        except (ValueError, OSError):
            # driver stream already closed at teardown; logging would
            # write to the same dead stream
            pass

    from .rpc import EventLoopThread
    EventLoopThread.get().post(
        worker.gcs.subscribe("WORKER_LOGS", _on_logs))


def _atexit_shutdown():
    try:
        shutdown()
    except Exception:
        pass


def shutdown():
    global _local_node
    worker = try_get_core_worker()
    if worker is not None:
        # Failures past this point are expected (the GCS may already be
        # gone); they must not arm reconnect probes.
        worker.gcs.suppress_reconnect()
        try:
            worker.gcs.call_sync("mark_job_finished", job_id=worker.job_id,
                                 timeout=10)
        except Exception:
            logger.debug("mark_job_finished failed during shutdown "
                         "(GCS already gone?)", exc_info=True)
        worker.shutdown()
        set_core_worker(None)
    if _local_node is not None:
        _local_node.stop()
        _local_node = None
    else:
        # Remote-cluster driver: no local node to tear down, but this
        # process's daemon threads still deserve a bounded join.
        from .threads import shutdown_daemon_threads
        shutdown_daemon_threads(timeout_s=2.0)
    CONFIG.reset()


def put(value: Any) -> ObjectRef:
    return get_core_worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    worker = get_core_worker()
    if isinstance(refs, ObjectRef):
        return worker.get([refs], timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError("get() expects an ObjectRef or a list of ObjectRefs")
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() got a non-ObjectRef: {type(r)}")
    return worker.get(list(refs), timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True
         ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    return get_core_worker().wait(list(refs), num_returns, timeout,
                                  fetch_local)


def kill(actor, *, no_restart: bool = True):
    from ..actor import ActorHandle
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    get_core_worker().gcs.call_sync("kill_actor", actor_id=actor.actor_id,
                                    no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    """Cancel a pending or running task (reference: worker.py cancel).

    The task's returns resolve to TaskCancelledError on get(). Tasks that
    have not started never run; running async actor tasks are
    asyncio-cancelled; running sync tasks are only stopped with force=True
    (worker process kill). Already-finished tasks are a no-op.
    """
    from .object_ref import ObjectRefGenerator
    if isinstance(ref, ObjectRefGenerator):
        ref = ref._generator_ref
        if ref is None:
            return  # already-materialized generator: task finished
    if not isinstance(ref, ObjectRef):
        raise TypeError("cancel() expects an ObjectRef or ObjectRefGenerator")
    get_core_worker().cancel_task(ref, force=force, recursive=recursive)


def cluster_resources() -> Dict[str, float]:
    view = get_core_worker().gcs.call_sync("get_cluster_view")
    out: Dict[str, float] = {}
    for info in view.values():
        for name, qty in info["total"].items():
            out[name] = out.get(name, 0.0) + qty
    return out


def available_resources() -> Dict[str, float]:
    view = get_core_worker().gcs.call_sync("get_cluster_view")
    out: Dict[str, float] = {}
    for info in view.values():
        for name, qty in info["available"].items():
            out[name] = out.get(name, 0.0) + qty
    return out


def nodes() -> List[Dict[str, Any]]:
    return get_core_worker().gcs.call_sync("get_all_nodes")


class RuntimeContext:
    """reference: python/ray/runtime_context.py"""

    def __init__(self, worker: CoreWorker):
        self._worker = worker

    @property
    def job_id(self) -> JobID:
        return self._worker.job_id

    @property
    def node_id(self) -> str:
        return self._worker.node_id

    @property
    def namespace(self) -> str:
        return _namespace

    def get_task_id(self):
        spec = RUNTIME_CTX.task_spec
        return spec.task_id if spec else None

    def get_actor_id(self):
        return RUNTIME_CTX.actor_id

    @property
    def was_current_actor_reconstructed(self) -> bool:
        spec = RUNTIME_CTX.task_spec
        return bool(spec and spec.attempt_number > 0)

    def gcs_address(self) -> Address:
        return self._worker.gcs.address


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(get_core_worker())
