"""Unified retry backoff (reference: retryable_grpc_client.cc's
exponential backoff + the scattered `delay *= 1.6` loops this replaces).

One policy object, three verbs:

    bo = Backoff(base_s=0.05, max_s=2.0, deadline_s=60.0)
    while True:
        try:
            return do_thing()
        except TransientError:
            if not bo.sleep():          # or: await bo.async_sleep()
                raise                   # deadline exhausted

Delays are jittered exponential: ``base * mult^attempt`` capped at
``max_s``, each multiplied by a uniform factor in [0.5, 1.5) so a herd
of reconnecting clients doesn't synchronize its retry storms. A
``deadline_s`` bounds the TOTAL time spent sleeping (None = retry
forever); ``next_delay()`` exposes the schedule without sleeping for
callers that drive their own waits (select loops, Event.wait).

rtpulint rule L009 flags raw ``time.sleep``/``asyncio.sleep`` calls in
retry loops inside ``_internal/`` — this module is the sanctioned
replacement (and is itself exempt, being the implementation).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Optional

logger = logging.getLogger(__name__)

__all__ = ["Backoff"]


class Backoff:
    """Jittered exponential backoff with a cap and an optional deadline.

    Not thread-safe: one instance per retry loop (they're cheap).

    ``site`` labels this loop in the ``rtpu_rpc_retries_total`` counter
    (rpc_metrics): every scheduled delay is one retry, counted from the
    shared primitive instead of hand-rolled per-call-site counters.
    Empty site = uncounted (ad-hoc loops that predate the label)."""

    __slots__ = ("base_s", "max_s", "mult", "deadline", "attempt", "site",
                 "_rng")

    def __init__(self, base_s: float = 0.05, max_s: float = 2.0,
                 mult: float = 2.0, deadline_s: Optional[float] = None,
                 seed: Optional[int] = None, site: str = ""):
        self.base_s = base_s
        self.max_s = max_s
        self.mult = mult
        self.deadline = (time.monotonic() + deadline_s
                         if deadline_s is not None else None)
        self.attempt = 0
        self.site = site
        # Seedable for deterministic tests; unseeded instances share no
        # state (each loop gets an independent stream).
        self._rng = random.Random(seed)

    # -- schedule ----------------------------------------------------------

    def next_delay(self) -> Optional[float]:
        """The next sleep in seconds, clamped to the remaining deadline,
        or None when the deadline is already exhausted. Advances the
        attempt counter."""
        raw = min(self.base_s * (self.mult ** self.attempt), self.max_s)
        self.attempt += 1
        if self.site:
            try:
                from . import rpc_metrics
                m = rpc_metrics.metrics()
                if m is not None:
                    m.retries.inc(tags={"site": self.site})
            except Exception:  # noqa: BLE001 — metrics never break a retry
                logger.debug("retry-site metric bump failed",
                             exc_info=True)
        delay = raw * (0.5 + self._rng.random())
        if self.deadline is not None:
            remaining = self.deadline - time.monotonic()
            if remaining <= 0:
                return None
            delay = min(delay, remaining)
        return delay

    def expired(self) -> bool:
        return self.deadline is not None \
            and time.monotonic() >= self.deadline

    def reset(self):
        """Back to the base delay (call after a success so the NEXT
        failure starts the schedule over)."""
        self.attempt = 0

    # -- sleeping ----------------------------------------------------------

    def sleep(self) -> bool:
        """Blocking sleep for the next delay. False = deadline exhausted
        (caller should give up)."""
        delay = self.next_delay()
        if delay is None:
            return False
        time.sleep(delay)
        return True

    async def async_sleep(self) -> bool:
        """asyncio sleep for the next delay. False = deadline exhausted."""
        delay = self.next_delay()
        if delay is None:
            return False
        await asyncio.sleep(delay)
        return True
