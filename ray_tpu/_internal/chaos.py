"""Deterministic fault injection (reference: src/ray/rpc/rpc_chaos.h,
grown into a registry).

The ad-hoc ``RTPU_TESTING_RPC_FAILURE`` env flag (drop requests/responses
by method substring) is promoted here into a seeded registry that the
RPC layer, tests, and ``cli chaos`` all drive:

- **RPC faults** by site key (method substring): ``drop_req`` /
  ``drop_resp`` (the legacy spec compiles to these), ``delay`` (hold the
  request ``param`` seconds before dispatch), ``dup`` (deliver the
  response frame twice — exercises caller idempotency).
- **Process faults**: ``kill_pid`` (SIGKILL — the worker/GCS ``kill -9``
  primitive for failover tests), plus the GCS/raylet ``set_chaos`` RPC
  handlers that let ``cli chaos set`` re-arm a live cluster.

Spec grammar (``CONFIG.chaos_spec``, comma-separated)::

    <method-substring>:<action>:<prob>[:<param>]
    e.g.  push_task:drop_resp:0.2 , heartbeat:delay:1.0:0.5 , kv_put:dup:0.1

The legacy ``CONFIG.testing_rpc_failure`` grammar
(``method:req_p:resp_p``) is still honored and folds into the same rule
table. All probability draws come from ONE ``random.Random`` seeded by
``CONFIG.chaos_seed`` (0 = process-random), so a failing chaos run
replays bit-identically under the same seed and call sequence.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from .config import CONFIG

logger = logging.getLogger(__name__)

_ACTIONS = ("drop_req", "drop_resp", "delay", "dup")


@dataclass
class Rule:
    pattern: str        # method substring
    action: str         # one of _ACTIONS
    prob: float
    param: float = 0.0  # delay seconds (delay action)


@dataclass
class ScheduledRule:
    """One time-scheduled fault entry: ``rule`` ARMS ``at_s`` seconds
    after the schedule itself was armed and stays active until a LATER
    entry for the same (pattern, action) replaces it — so
    ``5:hb:delay:1.0:0.2, 15:hb:delay:0`` injects a 200ms heartbeat
    delay only during t=[5, 15). Deterministic under ``chaos_seed``
    (all probability draws still come from the one seeded RNG), which
    is what lets the chaos soak replay its fault script bit-identically."""
    at_s: float
    rule: Rule


def parse_schedule(spec: str) -> List[ScheduledRule]:
    """Parse ``at_s:method:action:prob[:param],...`` — the scheduled
    variant of :func:`parse_spec`; malformed entries raise (a typo'd
    soak script must fail loudly, not soak nothing)."""
    entries: List[ScheduledRule] = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        parts = entry.split(":")
        if len(parts) < 4 or parts[2] not in _ACTIONS:
            raise ValueError(
                f"bad chaos schedule entry {entry!r}: want "
                "<at_s>:<method>:<drop_req|drop_resp|delay|dup>"
                ":<prob>[:<param>]")
        entries.append(ScheduledRule(
            at_s=float(parts[0]),
            rule=Rule(pattern=parts[1], action=parts[2],
                      prob=float(parts[3]),
                      param=float(parts[4]) if len(parts) > 4 else 0.0)))
    entries.sort(key=lambda s: s.at_s)
    return entries


def parse_spec(spec: str) -> List[Rule]:
    """Parse the extended grammar; raises ValueError on malformed entries
    (a typo'd chaos spec must fail loudly, not silently inject nothing)."""
    rules: List[Rule] = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        parts = entry.split(":")
        if len(parts) < 3 or parts[1] not in _ACTIONS:
            raise ValueError(
                f"bad chaos rule {entry!r}: want "
                "<method>:<drop_req|drop_resp|delay|dup>:<prob>[:<param>]")
        rules.append(Rule(pattern=parts[0], action=parts[1],
                          prob=float(parts[2]),
                          param=float(parts[3]) if len(parts) > 3 else 0.0))
    return rules


def parse_legacy_spec(spec: str) -> List[Rule]:
    """``method:req_p:resp_p`` (RTPU_TESTING_RPC_FAILURE back-compat)."""
    rules: List[Rule] = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        parts = entry.split(":")
        req_p, resp_p = float(parts[1]), float(parts[2])
        if req_p:
            rules.append(Rule(parts[0], "drop_req", req_p))
        if resp_p:
            rules.append(Rule(parts[0], "drop_resp", resp_p))
    return rules


class ChaosRegistry:
    """The process's fault-injection state. Rules reload lazily when the
    CONFIG specs change (tests monkeypatch CONFIG / env between runs);
    the RNG reseeds only when the seed value changes, so one test's
    draws don't perturb the next seeded run."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[Rule] = []
        self._schedule: List[ScheduledRule] = []
        self._armed_at: Optional[float] = None
        self._specs: Optional[tuple] = None
        self._rng = None
        self._seed_used: Optional[int] = None
        self._hits: Dict[str, int] = {}

    # -- rule table --------------------------------------------------------

    def _load(self):
        specs = (CONFIG.testing_rpc_failure, CONFIG.chaos_spec,
                 CONFIG.chaos_seed, CONFIG.chaos_schedule)
        if specs == self._specs:
            return
        with self._lock:
            if specs == self._specs:
                return
            rules: List[Rule] = []
            schedule: List[ScheduledRule] = []
            try:
                if specs[0]:
                    rules.extend(parse_legacy_spec(specs[0]))
                if specs[1]:
                    rules.extend(parse_spec(specs[1]))
                if specs[3]:
                    schedule = parse_schedule(specs[3])
            except (ValueError, IndexError):
                logger.exception("malformed chaos spec; injecting nothing")
                rules = []
                schedule = []
            import random
            seed = specs[2]
            if self._rng is None or seed != self._seed_used:
                self._rng = random.Random(seed if seed else None)
                self._seed_used = seed
            self._rules = rules
            if [s.rule for s in schedule] != \
                    [s.rule for s in self._schedule] or \
                    [s.at_s for s in schedule] != \
                    [s.at_s for s in self._schedule]:
                # t=0 of the script is the moment it was (re)armed.
                self._schedule = schedule
                self._armed_at = time.monotonic() if schedule else None
            self._specs = specs
            if rules or schedule:
                logger.warning(
                    "chaos armed: %d rule(s) + %d scheduled, seed=%s",
                    len(rules), len(schedule),
                    seed or "process-random")

    def arm(self, spec: str = "", seed: int = 0,
            legacy_spec: Optional[str] = None,
            schedule: Optional[str] = None):
        """Programmatic re-arm (tests / the set_chaos RPC): writes the
        specs into CONFIG so every read site — including freshly spawned
        code paths — sees the same rules, then reloads."""
        overrides: Dict[str, object] = {"chaos_spec": spec,
                                        "chaos_seed": seed}
        if legacy_spec is not None:
            overrides["testing_rpc_failure"] = legacy_spec
        if schedule is not None:
            overrides["chaos_schedule"] = schedule
        CONFIG.apply_system_config(overrides)
        self._specs = None
        if schedule is not None:
            # Re-arming the SAME schedule restarts its clock (a soak's
            # restart of an identical script must replay from t=0);
            # schedule=None (spec-only update) keeps the armed script
            # AND its clock.
            self._schedule = []
        self._load()

    def _effective_rules(self) -> List[Rule]:
        """Static rules plus the schedule's currently active entries;
        a later-activated scheduled entry REPLACES any earlier rule for
        the same (pattern, action) — `at:m:a:0` switches a fault off."""
        if not self._schedule or self._armed_at is None:
            return self._rules
        elapsed = time.monotonic() - self._armed_at
        merged: Dict[tuple, Rule] = {
            (r.pattern, r.action): r for r in self._rules}
        for entry in self._schedule:       # sorted by at_s
            if entry.at_s <= elapsed:
                merged[(entry.rule.pattern, entry.rule.action)] = \
                    entry.rule
        return list(merged.values())

    def active_rules(self) -> List[Rule]:
        self._load()
        return [r for r in self._effective_rules() if r.prob > 0]

    def schedule_status(self) -> List[Dict[str, object]]:
        """The armed schedule with per-entry activation state
        (`cli chaos show` prints these rows)."""
        self._load()
        if not self._schedule or self._armed_at is None:
            return []
        elapsed = time.monotonic() - self._armed_at
        return [{"at_s": e.at_s, "pattern": e.rule.pattern,
                 "action": e.rule.action, "prob": e.rule.prob,
                 "param": e.rule.param, "active": e.at_s <= elapsed,
                 "elapsed_s": round(elapsed, 2)}
                for e in self._schedule]

    def hit_counts(self) -> Dict[str, int]:
        """Per-(pattern, action) trigger counts — `cli chaos show` and
        tests assert injection actually happened (a vacuously green
        chaos test is worse than none)."""
        return dict(self._hits)

    # -- decision points (called from rpc.py) ------------------------------

    def _roll(self, method: str, action: str) -> Optional[Rule]:
        self._load()
        if not self._rules and not self._schedule:
            return None
        for rule in self._effective_rules():
            if rule.action == action and rule.pattern in method \
                    and self._rng.random() < rule.prob:
                key = f"{rule.pattern}:{rule.action}"
                self._hits[key] = self._hits.get(key, 0) + 1
                try:
                    from . import rpc_metrics
                    m = rpc_metrics.metrics()
                    if m is not None:
                        # method label = the rule's pattern (stable,
                        # bounded cardinality), not the matched method.
                        m.chaos_hits.inc(tags={"method": rule.pattern,
                                               "action": rule.action})
                except Exception:  # noqa: BLE001 — metrics never gate chaos
                    logger.debug("chaos-hit metric bump failed",
                                 exc_info=True)
                return rule
        return None

    def drop_request(self, method: str) -> bool:
        return self._roll(method, "drop_req") is not None

    def drop_response(self, method: str) -> bool:
        return self._roll(method, "drop_resp") is not None

    def request_delay(self, method: str) -> float:
        rule = self._roll(method, "delay")
        return rule.param if rule is not None else 0.0

    def duplicate_response(self, method: str) -> bool:
        return self._roll(method, "dup") is not None


REGISTRY = ChaosRegistry()


# ---------------------------------------------------------------------------
# process faults
# ---------------------------------------------------------------------------

def kill_pid(pid: int) -> bool:
    """SIGKILL a process — the ``kill -9`` primitive for failover tests
    and ``cli chaos kill-worker``. Returns False if the pid is gone."""
    try:
        os.kill(pid, signal.SIGKILL)
        return True
    except (ProcessLookupError, PermissionError) as e:
        logger.warning("chaos kill of pid %s failed: %s", pid, e)
        return False


async def handle_set_chaos(spec: str = "", seed: int = 0,
                           schedule: Optional[str] = None):
    """Shared RPC handler body (GCS + raylets register it): re-arm this
    process's registry — static rules and/or a time-scheduled script.
    ``schedule=None`` keeps an already-armed schedule (updating only
    the static rules must not silently disarm a running soak script);
    an explicit ``""`` clears it. An empty spec + empty schedule
    disarms everything (`cli chaos clear`)."""
    REGISTRY.arm(spec=spec, seed=seed, schedule=schedule)
    return {"rules": len(REGISTRY.active_rules()),
            "scheduled": len(REGISTRY.schedule_status()),
            "pid": os.getpid()}
