"""Runtime flag system.

Equivalent of the reference's `RAY_CONFIG` x-macro table
(src/ray/common/ray_config_def.h, 224 entries): a typed default table,
overridable per-process via `RTPU_<name>` environment variables and
cluster-wide via `init(_system_config={...})`.

Typed access:  `from ray_tpu._internal.config import CONFIG;
CONFIG.lease_idle_timeout_s`.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict

_DEFAULTS: Dict[str, Any] = {
    # --- RPC layer ---
    "rpc_connect_timeout_s": 10.0,
    "rpc_call_timeout_s": 60.0,
    "rpc_retry_base_delay_ms": 50,
    "rpc_retry_max_delay_ms": 2000,
    "rpc_max_retries": 5,
    # Fault injection: "method:req_prob:resp_prob,method2:..." — probability of
    # dropping the request / the response of matching RPC methods.
    # (Reference: src/ray/rpc/rpc_chaos.h RAY_testing_rpc_failure.)
    "testing_rpc_failure": "",
    # --- chaos harness (_internal/chaos.py) ---
    # Extended fault spec "method:action:prob[:param],..." with actions
    # drop_req / drop_resp / delay / dup; folds into one registry with
    # the legacy testing_rpc_failure rules.
    "chaos_spec": "",
    # Seed for the chaos RNG (0 = process-random). A fixed seed makes a
    # failing chaos run replayable bit-for-bit.
    "chaos_seed": 0,
    # Gate on the self-kill RPCs (`cli chaos kill-gcs`): a production
    # cluster must not expose a remote SIGKILL by default.
    "chaos_allow_kill": False,
    # Time-scheduled chaos script: "at_s:method:action:prob[:param],..."
    # — each entry ARMS its rule `at_s` seconds after the schedule is
    # armed (a later entry for the same method:action replaces the
    # earlier one, so `10:hb:delay:0` switches a fault off at t=10).
    # Deterministic under chaos_seed; `cli chaos show` prints the armed
    # schedule with per-entry activation state.
    "chaos_schedule": "",
    # --- fleet operations (drain / rolling upgrades) ---
    # Graceful-drain budget: how long a draining raylet waits for
    # in-flight leases to finish before stragglers get postmortem-tagged
    # kills (kill_reason=drain_timeout -> DRAIN_TIMEOUT_KILLED).
    "drain_timeout_s": 30.0,
    # --- elastic autoscaler (autoscaler/elastic.py) ---
    # Scale-up fires only after the pending-lease queue has been
    # non-empty AND older than queue_age_up_s for up_delay_s straight;
    # scale-in only after a node has been fully idle for down_delay_s.
    # Both delays are the hysteresis that keeps an oscillating queue
    # from flapping the fleet.
    "autoscale_queue_age_up_s": 1.0,
    "autoscale_up_delay_s": 2.0,
    "autoscale_down_delay_s": 15.0,
    # --- object store ---
    "object_store_memory_bytes": 2 * 1024**3,
    # Objects <= this many bytes are returned inline in RPC replies and live
    # in the in-process memory store instead of shared memory.
    "max_direct_call_object_size": 100 * 1024,
    "object_spilling_threshold": 0.8,
    # fsspec URL prefix for cloud spilling ("" = node-local directory);
    # e.g. "memory://rtpu-spill", "s3://bucket/prefix"
    # (reference: _private/external_storage.py:398 smart_open driver)
    "object_spilling_uri": "",
    "object_store_chunk_bytes": 4 * 1024**2,
    "spill_directory": "",  # default: <session dir>/spill
    # --- scheduling ---
    "scheduler_hybrid_threshold": 0.5,
    "lease_idle_timeout_s": 2.0,
    "worker_lease_parallelism": 10,
    "max_pending_lease_requests_per_shape": 10,
    # Pipelined task pushes per leased worker (reference:
    # normal_task_submitter.h max_tasks_in_flight_per_worker). The worker
    # executes serially; >1 hides push/reply latency behind execution.
    "max_tasks_in_flight_per_lease": 8,
    # Cooperative lease fairness: a driver flooding tasks returns each
    # lease to the raylet after holding it this long (the worker stays
    # warm in the raylet's idle pool), so other drivers' queued lease
    # requests get a turn instead of starving behind indefinitely-held
    # leases (multi-client flood fairness; reference: the raylet asks
    # for unused leased workers back, release_unused_workers).
    "lease_fair_rotation_s": 1.0,
    # Self-heal for lost pushes/replies WITHOUT bounding task duration
    # (tasks may legitimately run for hours): while a push_task call is
    # outstanding, the submitter probes the worker every period; if the
    # worker doesn't know the task for `threshold` consecutive probes,
    # the push (or its reply) was lost — drop the lease and retry.
    "push_probe_period_s": 15.0,
    "push_probe_unknown_threshold": 2,
    "push_probe_unreachable_threshold": 8,
    # --- device objects ---
    # HBM bytes the process may hold pinned for device-resident objects
    # (device_put_ref pins + DeviceChannel staging). 0 = unlimited.
    # Past the budget, producers BLOCK briefly for frees and then spill
    # to the host object store (reference: gpu_object_manager.py:61
    # tracks the same producer/consumer imbalance).
    "device_object_hbm_budget": 0,
    # How long device_put_ref blocks for frees before spilling to host.
    "device_object_backpressure_timeout_s": 10.0,
    # --- workers ---
    "worker_start_timeout_s": 60.0,
    "num_prestart_workers": 0,
    "worker_idle_timeout_s": 60.0,
    "maximum_startup_concurrency": 4,
    # --- health / failure detection ---
    "health_check_period_s": 1.0,
    "health_check_timeout_s": 5.0,
    "health_check_failure_threshold": 5,
    # driver (job) liveness: a crashed/os._exit'd driver's leases,
    # actors and PGs are reclaimed once its ping fails this many sweeps
    "driver_health_check_period_s": 3.0,
    "driver_health_check_failure_threshold": 3,
    "worker_liveness_check_period_s": 1.0,
    # --- gcs ---
    "gcs_storage": "memory",  # or a file path for persistence
    # Persistence path selector once a storage path exists:
    #   wal    — write-ahead log + compacted snapshot (durable per
    #            mutation, O(record) appends, torn-write detection)
    #   legacy — whole-state snapshot rewrite on every mutation (the
    #            pre-WAL behavior, kept as the A/B arm)
    #   off    — storage path ignored, nothing persisted
    "gcs_persist": "wal",
    # Compact (fold WAL into the snapshot) once the log passes this size.
    "gcs_wal_compact_bytes": 4 * 1024**2,
    # fsync appended records (group-committed per event-loop tick).
    # Off trades the last tick's mutations for bench-grade append speed.
    "gcs_wal_fsync": True,
    # Consecutive persist failures (disk full, permissions) before the
    # GCS emits a rate-limited GCS_PERSIST_FAILING event — durability
    # loss must be visible, not a logger.exception loop.
    "gcs_persist_failure_event_threshold": 3,
    # --- gcs failover / reconnect ---
    # Consecutive heartbeat failures before a raylet declares the GCS
    # down and enters its reconnect loop.
    "gcs_heartbeat_failure_threshold": 3,
    # Jittered-exponential reconnect schedule (raylets, drivers, the
    # serve controller and autoscaler all ride backoff.Backoff with
    # these bounds) and the total give-up deadline for client-side
    # reconnecting calls (0 = fail fast, no reconnect window).
    "gcs_reconnect_base_delay_ms": 50,
    "gcs_reconnect_max_delay_ms": 2000,
    "gcs_reconnect_timeout_s": 60.0,
    "pubsub_push_timeout_s": 5.0,
    # --- actors ---
    # Bound on actor __init__: a wedged-but-alive worker must fail the
    # creation (and reschedule) rather than park it forever.
    "actor_creation_timeout_s": 600.0,
    # Per-RPC bound on one actor lease request to a raylet. Generous by
    # default: the raylet's bounded spawn pipeline legitimately queues a
    # grant behind hundreds of spawns in an actor storm; retries after
    # this timeout coalesce onto the SAME in-flight grant raylet-side.
    "actor_lease_rpc_timeout_s": 600.0,
    # --- owner sharding (the multi-loop driver core) ---
    # Owner shards per CoreWorker: driver-side ownership state (lease /
    # pending tables, done-stream fold, probe sweeps, reply routing)
    # partitions across this many io loops, each with its own fastrpc
    # ring, keyed by hash(task_id/actor_id) % N. 0 = auto (min(4,
    # cores // 2) for drivers — sharding needs spare cores, small
    # boxes stay single-loop; always 1 for workers); 1 = the
    # exact-legacy single-loop A/B path.
    "owner_shards": 0,
    # --- tasks ---
    "task_max_retries_default": 3,
    "actor_max_restarts_default": 0,
    "max_lineage_bytes": 64 * 1024**2,
    "inline_arg_max_bytes": 100 * 1024,
    # --- memory monitor ---
    "memory_monitor_refresh_ms": 250,
    "memory_usage_threshold": 0.95,
    # Watermark BELOW the kill threshold at which the raylet starts
    # emitting MEMORY_PRESSURE events (reference: memory_monitor.h
    # usage_threshold vs min_memory_free_bytes two-level policy).
    "memory_monitor_watermark": 0.90,
    # Policy hook: stop granting NEW worker leases while node memory
    # sits above the watermark — requests queue (or spill back to a
    # healthy node) and grant once pressure clears; grant_or_reject
    # callers (actor scheduling) get a transient rejection instead.
    # Existing leases run on.
    "memory_pressure_refuse_leases": False,
    # --- cluster event log ---
    "event_log_max_entries": 10_000,
    # --- metrics ---
    "metrics_report_interval_s": 5.0,
    # --- continuous profiler (the CPU observability plane) ---
    # Default sampling rate for on-demand captures (cli profile /
    # profile_cluster) when the caller doesn't pass one.
    "profiler_hz": 100.0,
    # Bounded per-process sample ring (a sample is ~a few hundred bytes
    # of interned strings; overflow drops the oldest and counts it).
    "profiler_ring_size": 65536,
    # >0: every process (worker/raylet/GCS/driver) starts a continuous
    # sampler at boot at this rate. Off by default — captures start
    # samplers on demand.
    "profiler_autostart_hz": 0.0,
    # --- accelerator observability plane ---
    # HBM used/limit ratio above which device snapshots publish
    # DEVICE_MEMORY_PRESSURE events into the GCS event log (only on
    # backends that report a limit; rate-limited per device below).
    "accel_hbm_watermark": 0.90,
    "accel_pressure_min_interval_s": 30.0,
    # --- task events (reference: RAY_task_events_* flags) ---
    "enable_task_events": True,
    # --- logging / the log & forensics plane ---
    "log_to_driver": True,
    # Per-worker bounded log ring at the raylet (lines; overflow drops
    # the oldest and counts it). Rings retain output even with
    # log_to_driver off — the ring IS the retention layer.
    "log_ring_lines": 2000,
    # Dead workers' rings kept (FIFO) so `cli logs --task` and
    # postmortems still answer after the process is gone.
    "log_ring_dead_workers": 16,
    # Max concurrently in-flight WORKER_LOGS publishes per raylet: with
    # the GCS down/slow, batches beyond the window drop-with-counter
    # instead of queueing unboundedly on the EventLoopThread.
    "log_pump_inflight_max": 16,
    # Per-worker forwarding rate limit (lines/s; 0 = unlimited). Gates
    # pubsub streaming only — the bounded ring always captures.
    "log_rate_limit_lines_per_s": 0.0,
    # Lines of a dead worker's ring quoted in its postmortem report.
    "postmortem_tail_lines": 20,
    # How long a caller waits for the raylet's death report to reach
    # the GCS before raising WorkerCrashedError without a postmortem
    # (the liveness sweep runs every worker_liveness_check_period_s,
    # so the report usually lags the connection drop by ~1s).
    "postmortem_fetch_timeout_s": 2.0,
    # --- collectives backend (util/collective) ---
    # Algorithm forcing for the host-plane allreduce: auto picks per
    # (bytes, topology) — flat topologies keep the exact legacy
    # star/ring cutover, multi-slice topologies take the binomial tree
    # below the ring threshold and the hierarchical schedule (intra-
    # slice reduce-scatter, DCN allreduce of the shards, intra-slice
    # allgather) above it. ring/tree/hier/star force one arm for A/B.
    "collective_algo": "auto",
    # EQuARX-style block-int8 quantization of the hierarchical
    # schedule's inter-slice (DCN) hop: off (default, bit-exact) or
    # int8 (quantize per block, accumulate fp32, dequantize — SUM over
    # float payloads only; everything else stays exact).
    "collective_quant": "off",
    # Elements per quantization block (one fp32 scale per block).
    "collective_quant_block": 64,
    # --- owner-shard lease reclaim ---
    # With the owner core sharded, one shard's queued lease request can
    # starve behind ANOTHER shard's idle leases until the holder's 2s
    # idle-lease cleaner tick (observed as ~2s sync-get outliers at
    # RTPU_OWNER_SHARDS>=2). If a grant hasn't landed within this
    # delay, the requesting shard asks every other shard to return its
    # idle leases (zero in-flight, no local waiters) immediately.
    "lease_reclaim_delay_s": 0.1,
    # --- train-plane flight deck (steptrace / straggler / alerts) ---
    # Bounded per-process step-span ring (a span is 5 small fields;
    # overflow drops the oldest — steady-state loops keep the tail).
    "steptrace_max_spans": 4096,
    # Straggler detector: a peer whose collective entry-wait exceeds
    # BOTH the absolute floor and median_multiple x the median wait of
    # the other peers for `consecutive` collective ops in a row is
    # flagged (rate-limited per peer below).
    "straggler_median_multiple": 4.0,
    "straggler_consecutive_ops": 3,
    "straggler_min_wait_s": 0.02,
    "straggler_min_interval_s": 30.0,
    # SLO alert engine: evaluation tick of the daemon thread, and the
    # per-rule re-fire rate limit (a sustained breach is one alert per
    # interval, not one per tick).
    "alert_eval_interval_s": 5.0,
    "alert_min_interval_s": 60.0,
    # Bounded GCS alert table (rows beyond this drop the oldest).
    "alert_log_max_entries": 1000,
    # --- train ---
    "train_health_check_interval_s": 1.0,
    # GSPMD trainer: ZeRO-1 cross-replica sharded weight updates
    # (reduce-scatter grads, shard-local Adam on the 1/W optimizer
    # slice, allgather the param delta). RTPU_TRAIN_ZERO1=0 is the
    # replicated-update A/B arm (full optimizer state on every
    # replica, allreduce grads).
    "train_zero1": True,
    # MPMD pipeline: microbatches per GPipe round (bubble fraction is
    # (S-1)/(S-1+M) on parallel hardware; more microbatches = smaller
    # bubble, more in-flight activation memory).
    "train_pipeline_microbatches": 4,
    # --- LLM serving (llm/paged.py) ---
    # Prefix-cache entry ceiling: radix-tree nodes (continuous batching)
    # or token-tuple LRU entries (legacy arm) kept before LRU eviction
    # of refcount-1 leaves. Each entry pins one KV page.
    "prefix_cache_entries": 128,
    # --- serve-plane request observatory (llm/reqtrace.py) ---
    # Bounded per-process request-lifecycle event ring (an event is 4
    # small fields; overflow drops the oldest — steady-state serving
    # keeps the tail).
    "reqtrace_max_events": 8192,
    # Serve SLO thresholds for the default alert rules (alerts.py):
    # TTFT p95 over the window, max lease-queue age, and max KV-page
    # occupancy fraction before an alert fires.
    "serve_ttft_p95_slo_s": 2.0,
    "serve_queue_age_slo_s": 30.0,
    "serve_kv_occupancy_slo": 0.95,
    # --- RPC/transport observatory (_internal/rpc_metrics.py) ---
    # Any client call slower than this lands in the slow-RPC watchdog
    # ring with method + peer + creation-site attribution.
    "rpc_slow_call_s": 1.0,
    # Bounded watchdog ring (a row is 6 small fields; overflow drops
    # the oldest).
    "rpc_slow_ring_size": 256,
    # Rate limit for the SLOW_RPC GCS event the watchdog posts (one
    # event per window per process; the ring keeps everything).
    "rpc_slow_event_interval_s": 30.0,
    # Transport SLO thresholds for the default alert rules (alerts.py):
    # client-call p99 over the window, and max native-ring queue depth
    # before the ring_backpressure alert fires.
    "rpc_client_p99_slo_s": 5.0,
    "ring_backpressure_depth": 4096,
    # --- A/B kill switches (every switch lives here so a typo'd
    # RTPU_* spelling is caught by rtpulint rule L003 instead of
    # silently doing nothing) ---
    # Disable the flat-wire task codec; every spec rides the pickle path.
    "no_flat_wire": False,
    # Disable the native receive path (PR 11): frames are delivered raw
    # and decoded in Python, done streams ride the legacy pickled
    # oneway, and refcount decrements go one RPC per object — the
    # exact-legacy A/B arm. Receivers still understand both wire forms,
    # so mixed on/off processes interoperate.
    "no_native_decode": False,
    # Disable owner callsite capture on put()/submit.
    "no_callsites": False,
    # Disable the coalesced submit fast path.
    "no_submit_fastpath": False,
    # Disable asyncio eager task factory on the io loop.
    "no_eager_tasks": False,
    # Kill switch for the stack-sampling profiler: start_profiling
    # refuses and no sampler thread is ever spawned.
    "no_profiler": False,
    # Kill switch for the accelerator observability plane: zero
    # jax.monitoring listeners installed, device snapshots return
    # empty, StepTimer/report_step are no-ops.
    "no_accel_metrics": False,
    # Kill switch for the log & forensics plane: no stream stamping in
    # workers, no raylet rings, exact-legacy pump wiring (DEVNULL with
    # log_to_driver off), no postmortem assembly — zero extra threads.
    "no_log_plane": False,
    # Kill switch for the cross-rank step timeline: span() degrades to
    # a no-op context (one flag check), nothing is recorded or flushed,
    # and the collective straggler detector stops attributing waits.
    "no_steptrace": False,
    # Kill switch for the serve-plane request observatory: record()
    # degrades to one flag check, no lifecycle ring is ever
    # constructed, nothing piggybacks on the metrics flush —
    # exact-legacy behavior with zero rings and zero extra threads.
    "no_reqtrace": False,
    # Kill switch for continuous batching in the paged LLM engine:
    # exact-legacy per-drain admission (blocking inline prefill, upfront
    # page reservation, token-tuple prefix LRU, no preemption).
    "no_cont_batch": False,
    # Kill switch for the RPC/transport observatory: zero rpc/ring/chaos
    # series constructed, no slow-RPC watchdog ring, no frame-meta trace
    # propagation — exact-legacy frames on the wire, so mixed on/off
    # processes interoperate.
    "no_rpc_metrics": False,
    # --- event-loop stall sanitizer (_internal/lint/loopstall.py) ---
    # Armed together with the lock-order sanitizer (RTPU_SANITIZE=1):
    # any single callback that holds a ray_tpu-owned event loop longer
    # than this budget is recorded with its creation site. 0 disables
    # recording even when sanitized.
    "loopstall_budget_ms": 50.0,
    # --- overrides re-read from the environment at their use site
    # (tests monkeypatch them after CONFIG construction; registered here
    # so L003 can resolve the names) ---
    # Force the pure-asyncio RPC transport even when fastrpc built
    # (fastrpc.py reads the env at attach time).
    "disable_native_rpc": False,
    # Container runtime binary for image_uri runtime envs ("" = autodetect).
    "container_runtime": "",
    # TPU chip count override (0 = autodetect).
    "num_tpu_chips": 0,
    # Bind host for the device-object transfer server.
    "transfer_host": "127.0.0.1",
}

_ENV_PREFIX = "RTPU_"

# Process-plumbing environment variables: per-process bootstrap channel
# (raylet -> worker) and tooling gates, NOT tunable config flags — they
# carry identities/addresses, so they have no sensible default row in
# _DEFAULTS. rtpulint L003 resolves RTPU_* env reads against _DEFAULTS
# first, then this set.
BOOTSTRAP_ENV = frozenset({
    "RTPU_WORKER_ID", "RTPU_SESSION", "RTPU_NODE_ID", "RTPU_NODE_INDEX",
    "RTPU_RAYLET_ADDR", "RTPU_GCS_ADDR", "RTPU_WORKER_PROFILE",
    "RTPU_SANITIZE", "RTPU_NATIVE_CACHE", "RTPU_NATIVE_DEBUG",
})


def _coerce(value: str, default: Any) -> Any:
    if isinstance(default, bool):
        return value.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(value)
    if isinstance(default, float):
        return float(value)
    if isinstance(default, (dict, list)):
        return json.loads(value)
    return value


class _Config:
    def __init__(self):
        self._lock = threading.Lock()
        self._values = dict(_DEFAULTS)
        self._load_env()

    def _load_env(self):
        for name, default in _DEFAULTS.items():
            # Canonical spelling is RTPU_<NAME> (uppercase — what the
            # docs, tests, and kill-switch runbooks use); the historical
            # exact-case form is honored as a fallback. Before this,
            # uppercase overrides of lowercase flag names silently did
            # nothing (e.g. the RTPU_TESTING_RPC_FAILURE chaos spec
            # never reached CONFIG in spawned workers).
            env = os.environ.get(_ENV_PREFIX + name.upper())
            if env is None:
                env = os.environ.get(_ENV_PREFIX + name)
            if env is not None:
                self._values[name] = _coerce(env, default)

    def __getattr__(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(f"unknown config flag: {name}") from None

    def get(self, name: str, default: Any = None) -> Any:
        return self._values.get(name, default)

    def known_flags(self):
        """Registered flag names (for rtpulint L003 and tooling)."""
        return frozenset(_DEFAULTS)

    def apply_system_config(self, overrides: Dict[str, Any]):
        with self._lock:
            for name, value in overrides.items():
                if name not in _DEFAULTS:
                    raise ValueError(f"unknown config flag: {name}")
                self._values[name] = value

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._values)

    def reset(self):
        with self._lock:
            self._values = dict(_DEFAULTS)
            self._load_env()


CONFIG = _Config()
