"""CoreWorker: the per-process runtime.

Equivalent of the reference CoreWorker (src/ray/core_worker/: core_worker.h,
reference_count.cc, task_manager.cc, normal_task_submitter.cc,
actor_task_submitter.cc, task_execution/, object_recovery_manager.cc). Linked
into every driver and worker process. Owns:

- the in-process memory store (small/inlined objects) + shm store access
- distributed ownership: reference counting with borrower accounting
- TaskManager: pending tasks, retries, lineage retention for reconstruction
- NormalTaskSubmitter: lease-based scheduling — ask a raylet for a worker
  lease, push the task directly to the leased worker, reuse leases for
  same-shape tasks until idle timeout
- ActorTaskSubmitter: direct worker-to-worker calls with sequence numbers,
  queueing across restarts, death propagation
- the execution loop (worker mode): ordered actor queues, concurrency
  groups, async actors on the event loop
- object recovery: lost plasma objects are rebuilt by resubmitting the
  creating task from retained lineage
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import logging
import os
import struct
import sys
import threading
import time
import traceback
import dataclasses
from dataclasses import dataclass, field

import numpy as np
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from . import aio
from .backoff import Backoff
from .config import CONFIG
from .errors import (ActorDiedError, ActorUnavailableError, GetTimeoutError,
                     ObjectLostError, RayTpuError, TaskError,
                     WorkerCrashedError)
from .gcs_client import GcsClient
from .ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from .memory_store import MemoryStore, resolve_entry
from . import native_decode
from .object_ref import ObjectRef
from .owner_shards import (OwnerShard, ShardSet,
                           fire_and_forget as _fire_and_forget,
                           resolve_shard_count, route_bytes)
from .plasma import PlasmaDir
from . import profiler
from .rpc import Address, ClientPool, EventLoopThread, RpcServer
from . import serialization
from . import task_spec as task_spec_codec
from .task_spec import (ACTOR_CREATION_TASK, ACTOR_TASK, NORMAL_TASK,
                        FunctionManager, TaskArg, TaskSpec, _CallBundle,
                        _RefPlaceholder)

logger = logging.getLogger(__name__)

_global_worker: Optional["CoreWorker"] = None
_global_lock = threading.Lock()


class _SpreadMark:
    """Unique sentinel marking one-shot SPREAD lease keys. A class (not
    object()) so the mark survives pickling of key tuples; identity
    is restored via __reduce__ returning the singleton."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __reduce__(self):
        return (_SpreadMark, ())


_SPREAD = _SpreadMark()


def _is_spread_key(key) -> bool:
    return key is not None and len(key) >= 2 and key[-2] is _SPREAD


def get_core_worker() -> "CoreWorker":
    if _global_worker is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first")
    return _global_worker


def try_get_core_worker() -> Optional["CoreWorker"]:
    return _global_worker


def set_core_worker(worker: Optional["CoreWorker"]):
    global _global_worker
    with _global_lock:
        _global_worker = worker


# ---------------------------------------------------------------------------
# Reference counting (reference: src/ray/core_worker/reference_count.cc)
# ---------------------------------------------------------------------------

# Callsite capture for `ray memory`-style attribution. Read ONCE from
# the registered flag table: a lookup per put()/submit would sit on the
# hot path (RTPU_NO_CALLSITES=1 kill switch).
_NO_CALLSITES = bool(CONFIG.no_callsites)
# Trailing separator: a bare prefix would also swallow sibling dirs
# like .../ray_tpu_addons and misattribute their frames.
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) \
    + os.sep
# (code object, lineno) -> rendered site; call sites repeat across loops
# so the f-string render happens once per distinct site, not per call.
_callsite_cache: Dict[Tuple[Any, int], str] = {}


def _capture_callsite() -> Optional[str]:
    """First stack frame outside the ray_tpu package, as
    "file.py:lineno:function" (reference: CoreWorker ref creation
    callsites feeding `ray memory`). ~1us warm; disabled entirely by
    RTPU_NO_CALLSITES=1."""
    if _NO_CALLSITES:
        return None
    frame = sys._getframe(1)
    depth = 0
    while frame is not None and depth < 16:
        code = frame.f_code
        if not code.co_filename.startswith(_PKG_DIR):
            key = (code, frame.f_lineno)
            site = _callsite_cache.get(key)
            if site is None:
                if len(_callsite_cache) > 4096:
                    _callsite_cache.clear()
                site = (f"{code.co_filename}:{frame.f_lineno}:"
                        f"{code.co_name}")
                _callsite_cache[key] = site
            return site
        frame = frame.f_back
        depth += 1
    return None


@dataclass
class RefEntry:
    local: int = 0
    submitted: int = 0        # pending tasks that take this ref as an arg
    borrowers: int = 0        # remote processes holding a deserialized copy
    contained_in: int = 0     # live outer objects embedding this ref
    is_owner: bool = False
    in_plasma: bool = False
    owner_address: Optional[Address] = None
    lineage_task: Optional[TaskID] = None
    size: int = 0             # serialized bytes (0 = unknown yet)
    callsite: Optional[str] = None  # creation site (put()/task submit)

    def total(self) -> int:
        return self.local + self.submitted + self.borrowers + self.contained_in


def classify_reference(entry: RefEntry) -> str:
    """Reference-kind classification for memory reports (reference: the
    ray memory row types out of reference_count.cc). Precedence: a ref
    held by a pending task outranks mere store residency — the question
    a leak hunt asks is "what is KEEPING this object alive"."""
    if not entry.is_owner:
        return "BORROWED"
    if entry.submitted > 0:
        return "USED_BY_PENDING_TASK"
    if entry.contained_in > 0:
        return "CAPTURED_IN_ACTOR"
    if entry.in_plasma:
        return "PINNED_IN_OBJECT_STORE"
    return "LOCAL_REFERENCE"


class ReferenceCounter:
    def __init__(self, core_worker: "CoreWorker"):
        self._cw = core_worker
        self._lock = threading.Lock()
        self._entries: Dict[ObjectID, RefEntry] = {}
        # (deadline, oid) FIFO — appended with monotonically increasing
        # deadlines (constant ttl), so the head is always the earliest.
        self._transit_pins: collections.deque = collections.deque()
        self._sweeper_thread: Optional[threading.Thread] = None
        self._sweeper_stop = threading.Event()

    def _entry(self, object_id: ObjectID) -> RefEntry:
        entry = self._entries.get(object_id)
        if entry is None:
            entry = RefEntry()
            self._entries[object_id] = entry
        return entry

    def add_owned(self, object_id: ObjectID, in_plasma: bool = False,
                  lineage_task: Optional[TaskID] = None,
                  size: int = 0, callsite: Optional[str] = None):
        with self._lock:
            entry = self._entry(object_id)
            entry.is_owner = True
            entry.in_plasma = entry.in_plasma or in_plasma
            entry.lineage_task = lineage_task
            if size:
                entry.size = size
            if callsite is not None:
                entry.callsite = callsite

    def new_owned_ref(self, object_id: ObjectID, owner_address: Address,
                      lineage_task: Optional[TaskID] = None,
                      callsite: Optional[str] = None) -> ObjectRef:
        """add_owned + the ObjectRef's add_local_ref in ONE lock
        acquisition — the submit hot path creates one owned ref per
        return and the two separate locked calls showed up in n:n
        profiles."""
        ref = ObjectRef(object_id, owner_address, _register=False)
        with self._lock:
            entry = self._entry(object_id)
            entry.is_owner = True
            entry.lineage_task = lineage_task
            entry.local += 1
            entry.callsite = callsite
            if entry.owner_address is None:
                entry.owner_address = owner_address
        ref._registered = True
        return ref

    def set_sizes(self, pairs: List[Tuple[ObjectID, int]]):
        """Record serialized sizes for a completed task's returns under
        ONE lock acquisition (mirrors the batched decrement discipline —
        completions must not reintroduce per-object locking)."""
        if not pairs:
            return
        with self._lock:
            for object_id, size in pairs:
                entry = self._entries.get(object_id)
                if entry is not None:
                    entry.size = size

    def mark_in_plasma(self, object_id: ObjectID):
        with self._lock:
            self._entry(object_id).in_plasma = True

    def add_local_ref(self, ref: ObjectRef):
        with self._lock:
            entry = self._entry(ref.id())
            entry.local += 1
            if entry.owner_address is None:
                entry.owner_address = ref.owner_address()

    def remove_local_ref(self, ref: ObjectRef):
        self._decrement(ref.id(), "local")

    def add_submitted(self, object_ids: List[ObjectID]):
        with self._lock:
            for oid in object_ids:
                self._entry(oid).submitted += 1

    def remove_submitted(self, object_ids: List[ObjectID]):
        self._decrement_many(object_ids, "submitted")

    def add_contained(self, object_ids: List[ObjectID]):
        with self._lock:
            for oid in object_ids:
                self._entry(oid).contained_in += 1

    def remove_contained(self, object_ids: List[ObjectID]):
        self._decrement_many(object_ids, "contained_in")

    def add_borrower(self, object_id: ObjectID):
        with self._lock:
            self._entry(object_id).borrowers += 1

    def remove_borrower(self, object_id: ObjectID):
        self._decrement(object_id, "borrowers")

    def on_ref_deserialized(self, ref: ObjectRef):
        """We just became a borrower of a ref owned elsewhere."""
        owner = ref.owner_address()
        if owner is None or owner == self._cw.rpc_address:
            return
        self._cw.fire_and_forget(owner, "borrow_addref",
                                 object_hex=ref.hex())

    def _decrement(self, object_id: ObjectID, kind: str):
        # Single-object path kept tuple-free: remove_local_ref runs once
        # per ObjectRef finalizer on call floods.
        free = False
        notify_owner = None
        in_plasma = False
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                return
            setattr(entry, kind, max(0, getattr(entry, kind) - 1))
            if entry.total() == 0:
                del self._entries[object_id]
                in_plasma = entry.in_plasma
                if entry.is_owner:
                    free = True
                elif entry.owner_address is not None:
                    notify_owner = entry.owner_address
        if free:
            self._cw._free_owned_object(object_id, in_plasma=in_plasma)
        elif notify_owner is not None:
            self._cw.queue_borrow_decref(notify_owner, object_id)

    def _decrement_many(self, object_ids, kind: str):
        """Release a batch of refs of one kind under ONE lock acquisition
        (a completing task's dep list used to take the lock per object —
        measurable on call floods); the resulting frees / owner
        notifications run outside the lock."""
        if not object_ids:
            return
        frees: List[Tuple[ObjectID, bool]] = []
        notify: List[Tuple[ObjectID, Address]] = []
        with self._lock:
            for object_id in object_ids:
                entry = self._entries.get(object_id)
                if entry is None:
                    continue
                setattr(entry, kind, max(0, getattr(entry, kind) - 1))
                if entry.total() == 0:
                    del self._entries[object_id]
                    if entry.is_owner:
                        frees.append((object_id, entry.in_plasma))
                    elif entry.owner_address is not None:
                        notify.append((object_id, entry.owner_address))
        for object_id, in_plasma in frees:
            self._cw._free_owned_object(object_id, in_plasma=in_plasma)
        for object_id, owner in notify:
            self._cw.queue_borrow_decref(owner, object_id)

    def remove_borrowers_fold(self, object_ids: List[ObjectID]):
        """Apply one decref fold (a batch of borrower decrements that
        arrived as a single contiguous id array) under ONE lock
        acquisition — the receive-side twin of the sender's
        _decrement_many batching."""
        self._decrement_many(object_ids, "borrowers")

    def is_owner(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry is not None and entry.is_owner

    def pin_for_transit(self, refs, ttl: float = 60.0):
        """Pin owned refs being serialized into an outbound reply.

        Without this, an owner can free an object in the gap between
        sending a reply containing its ref and the receiver's async
        borrow_addref arriving (reference: the borrower protocol in
        reference_count.cc closes this with ownership 'borrowed refs'
        bookkeeping piggybacked on task replies; a TTL pin is the simple
        equivalent — the real borrower's addref takes over within the
        window or the object was never fetched). Expiry is handled by ONE
        sweeper thread over a deadline queue, not a thread per pin."""
        pinned = False
        for ref in refs:
            oid = ref.id()
            if not self.is_owner(oid):
                continue
            self.add_borrower(oid)
            self._transit_pins.append((time.monotonic() + ttl, oid))
            pinned = True
        # Liveness-keyed (a signaled-but-not-yet-exited sweeper counts
        # as stopped): a pin landing in the window between node
        # teardown's stop signal and the old thread's exit still gets a
        # live sweeper — a boolean flag lost that race and leaked the
        # pins' borrower refs. Unlocked pre-check keeps the common case
        # (sweeper running) lock-free; the decision re-checks under the
        # lock, and the spawn happens under it too, so ident is set
        # before anyone else looks.
        t = self._sweeper_thread
        if pinned and (t is None or not t.is_alive()
                       or self._sweeper_stop.is_set()):
            with self._lock:
                t = self._sweeper_thread
                if t is None or not t.is_alive() \
                        or self._sweeper_stop.is_set():
                    stop = threading.Event()
                    self._sweeper_stop = stop
                    from .threads import spawn_daemon
                    self._sweeper_thread = spawn_daemon(
                        self._sweep_transit_pins, args=(stop,),
                        name="rtpu-transit-sweeper", stop=stop.set)

    def _sweep_transit_pins(self, stop: threading.Event):
        while not stop.wait(1.0):
            now = time.monotonic()
            while self._transit_pins and self._transit_pins[0][0] <= now:
                _deadline, oid = self._transit_pins.popleft()
                self.remove_borrower(oid)

    def num_refs(self) -> int:
        with self._lock:
            return len(self._entries)

    def memory_report(self, limit: int = 10_000) -> List[Dict[str, Any]]:
        return self.memory_report_with_meta(limit)[0]

    def memory_report_with_meta(self, limit: int = 10_000
                                ) -> Tuple[List[Dict[str, Any]], bool]:
        """Per-object introspection rows for `get_memory_report` / `ray
        memory` (reference: reference_count.cc AddObjectRefStats), plus
        a truncation flag derived from the SAME snapshot — comparing a
        later num_refs() against len(rows) would race concurrent
        puts/submits and spuriously read as truncation. ONE lock
        acquisition snapshots the table; rendering runs outside it
        (benign reads of mutable entries — observability tolerates a
        racing decrement)."""
        with self._lock:
            items = list(self._entries.items())
        rows = []
        for oid, entry in items:
            rows.append({
                "object_id": oid.hex(),
                "size": entry.size,
                "kind": classify_reference(entry),
                "callsite": entry.callsite,
                "local": entry.local,
                "submitted": entry.submitted,
                "borrowers": entry.borrowers,
                "contained_in": entry.contained_in,
                "is_owner": entry.is_owner,
                "in_plasma": entry.in_plasma,
            })
        truncated = len(rows) > limit
        if truncated:
            rows.sort(key=lambda r: -r["size"])
            rows = rows[:limit]
        return rows, truncated


class ShardedReferenceCounter:
    """Owner-sharded reference table (reference: the reference's
    reference_count.cc partitions its mutex by shard inside the
    multithreaded core worker). N independent ReferenceCounter slices
    keyed by object-id hash: unrelated ids never contend on one lock,
    and ``ObjectID.for_task_return`` shares its task's routing prefix so
    a task's returns land in one slice. Safe from any thread, exactly
    like the single-slice counter; batch operations split per slice and
    keep the one-lock-per-dep-list discipline within each.

    Only constructed for shard counts > 1 — ``RTPU_OWNER_SHARDS=1``
    instantiates the plain ReferenceCounter (exact-legacy A/B path)."""

    def __init__(self, core_worker: "CoreWorker", count: int):
        self._count = count
        self._stripes = [ReferenceCounter(core_worker)
                         for _ in range(count)]

    def _for(self, object_id: ObjectID) -> ReferenceCounter:
        return self._stripes[route_bytes(object_id.binary(), self._count)]

    def _split(self, object_ids) -> Dict[int, List[ObjectID]]:
        buckets: Dict[int, List[ObjectID]] = {}
        count = self._count
        for oid in object_ids:
            buckets.setdefault(route_bytes(oid.binary(), count),
                               []).append(oid)
        return buckets

    # -- per-object ops: route to the owning slice ----------------------

    def add_owned(self, object_id: ObjectID, **kwargs):
        self._for(object_id).add_owned(object_id, **kwargs)

    def new_owned_ref(self, object_id: ObjectID, owner_address: Address,
                      lineage_task: Optional[TaskID] = None,
                      callsite: Optional[str] = None) -> ObjectRef:
        return self._for(object_id).new_owned_ref(
            object_id, owner_address, lineage_task=lineage_task,
            callsite=callsite)

    def mark_in_plasma(self, object_id: ObjectID):
        self._for(object_id).mark_in_plasma(object_id)

    def add_local_ref(self, ref: ObjectRef):
        self._for(ref.id()).add_local_ref(ref)

    def remove_local_ref(self, ref: ObjectRef):
        self._for(ref.id()).remove_local_ref(ref)

    def add_borrower(self, object_id: ObjectID):
        self._for(object_id).add_borrower(object_id)

    def remove_borrower(self, object_id: ObjectID):
        self._for(object_id).remove_borrower(object_id)

    def remove_borrowers_fold(self, object_ids: List[ObjectID]):
        for idx, chunk in self._split(object_ids).items():
            self._stripes[idx].remove_borrowers_fold(chunk)

    def on_ref_deserialized(self, ref: ObjectRef):
        self._for(ref.id()).on_ref_deserialized(ref)

    def is_owner(self, object_id: ObjectID) -> bool:
        return self._for(object_id).is_owner(object_id)

    # -- batch ops: split once, one lock acquisition per slice ----------

    def set_sizes(self, pairs: List[Tuple[ObjectID, int]]):
        if not pairs:
            return
        count = self._count
        buckets: Dict[int, List[Tuple[ObjectID, int]]] = {}
        for oid, size in pairs:
            buckets.setdefault(route_bytes(oid.binary(), count),
                               []).append((oid, size))
        for idx, chunk in buckets.items():
            self._stripes[idx].set_sizes(chunk)

    def add_submitted(self, object_ids: List[ObjectID]):
        for idx, chunk in self._split(object_ids).items():
            self._stripes[idx].add_submitted(chunk)

    def remove_submitted(self, object_ids):
        for idx, chunk in self._split(object_ids).items():
            self._stripes[idx].remove_submitted(chunk)

    def add_contained(self, object_ids: List[ObjectID]):
        for idx, chunk in self._split(object_ids).items():
            self._stripes[idx].add_contained(chunk)

    def remove_contained(self, object_ids):
        for idx, chunk in self._split(object_ids).items():
            self._stripes[idx].remove_contained(chunk)

    def pin_for_transit(self, refs, ttl: float = 60.0):
        count = self._count
        buckets: Dict[int, list] = {}
        for ref in refs:
            buckets.setdefault(route_bytes(ref.id().binary(), count),
                               []).append(ref)
        for idx, chunk in buckets.items():
            self._stripes[idx].pin_for_transit(chunk, ttl=ttl)

    # -- introspection: fold across slices ------------------------------

    def num_refs(self) -> int:
        return sum(s.num_refs() for s in self._stripes)

    def memory_report(self, limit: int = 10_000) -> List[Dict[str, Any]]:
        return self.memory_report_with_meta(limit)[0]

    def memory_report_with_meta(self, limit: int = 10_000
                                ) -> Tuple[List[Dict[str, Any]], bool]:
        rows: List[Dict[str, Any]] = []
        truncated = False
        for stripe in self._stripes:
            chunk, trunc = stripe.memory_report_with_meta(limit)
            rows.extend(chunk)
            truncated = truncated or trunc
        if len(rows) > limit:
            rows.sort(key=lambda r: -r["size"])
            rows = rows[:limit]
            truncated = True
        return rows, truncated


# ---------------------------------------------------------------------------
# Task event buffer (reference: src/ray/core_worker/task_event_buffer.cc —
# batches task state transitions and flushes them to the GCS task manager,
# feeding the state API / timeline)
# ---------------------------------------------------------------------------

_UNSET = object()


class TaskEventBuffer:
    def __init__(self, core_worker: "CoreWorker"):
        self._cw = core_worker
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._flusher_started = False
        self._worker_hex = _UNSET  # lazy: worker_id may be set post-init
        self._hex_cache: Dict[Any, str] = {}  # job/actor ids repeat

    def record(self, spec: "TaskSpec", event: str, **extra):
        if not CONFIG.enable_task_events or not spec.enable_task_events:
            return
        # Hot path snapshots the MUTABLE spec fields (attempt/name flip
        # on retries and cancellation tombstones) but defers the hex/dict
        # rendering to the once-a-second flush (~20us/event saved on
        # call floods).
        item = (spec.task_id, spec.attempt_number,
                spec.name or spec.function.display_name(), spec.job_id,
                spec.task_type, spec.actor_id, event, time.time(), extra)
        with self._lock:
            self._events.append(item)
            if len(self._events) > 10_000:  # drop oldest under pressure
                del self._events[:5_000]
            if not self._flusher_started:
                self._flusher_started = True
                self._cw.loop_call(self._flush_loop())

    def _render(self, item) -> Dict[str, Any]:
        (task_id, attempt, name, job_id, task_type, actor_id, event,
         ts, extra) = item
        wid = self._worker_hex
        if wid is _UNSET:
            # Cache ONLY once the worker id is real bytes: the first
            # flush can precede worker-id assignment, and caching the
            # None would strip worker attribution from every timeline
            # event this process ever emits.
            if isinstance(self._cw.worker_id, bytes):
                wid = self._worker_hex = self._cw.worker_id.hex()
            else:
                wid = None
        if len(self._hex_cache) > 4096:
            self._hex_cache.clear()
        jid = self._hex_cache.get(job_id)
        if jid is None:
            jid = self._hex_cache[job_id] = job_id.hex()
        aid = None
        if actor_id:
            aid = self._hex_cache.get(actor_id)
            if aid is None:
                aid = self._hex_cache[actor_id] = actor_id.hex()
        ev = {
            "task_id": task_id.hex(),
            "attempt": attempt,
            "name": name,
            "job_id": jid,
            "type": task_type,
            "actor_id": aid,
            "event": event,
            "ts": ts,
            "worker_id": wid,
            "node_index": self._cw.node_index,
        }
        ev.update(extra)
        return ev

    async def _flush_loop(self):
        while True:
            await asyncio.sleep(1.0)
            with self._lock:
                batch, self._events = self._events, []
            if batch:
                try:
                    await self._cw.gcs.call(
                        "add_task_events",
                        events=[self._render(i) for i in batch])
                except Exception:  # noqa: BLE001 — observability best-effort
                    logger.debug("task-event flush to GCS failed "
                                 "(dropping %d events)", len(batch),
                                 exc_info=True)


# ---------------------------------------------------------------------------
# Task manager (reference: src/ray/core_worker/task_manager.cc)
# ---------------------------------------------------------------------------

@dataclass
class PendingTask:
    spec: TaskSpec
    retries_left: int
    start_time: float = field(default_factory=time.time)
    # Dependency snapshot taken at submit time (the submitter may later
    # inline resolved ref args in place, so the spec can't be re-derived).
    dep_ids: List[ObjectID] = field(default_factory=list)
    contained_ids: List[ObjectID] = field(default_factory=list)


class TaskManager:
    def __init__(self, core_worker: "CoreWorker"):
        self._cw = core_worker
        self._lock = threading.Lock()
        self.pending: Dict[TaskID, PendingTask] = {}
        self.lineage: Dict[TaskID, TaskSpec] = {}
        self.cancelled: Set[TaskID] = set()
        self._lineage_bytes = 0

    def add_pending(self, spec: TaskSpec,
                    dep_ids: Optional[List[ObjectID]] = None,
                    contained_ids: Optional[List[ObjectID]] = None):
        if dep_ids is None:
            dep_ids = [oid for oid, _ in spec.dependencies()]
        if contained_ids is None:
            contained_ids = [c for a in spec.args
                             for c in a.contained_ref_ids]
        with self._lock:
            self.pending[spec.task_id] = PendingTask(
                spec=spec, retries_left=spec.max_retries,
                dep_ids=dep_ids, contained_ids=contained_ids)
        self._cw.task_events.record(spec, "SUBMITTED")

    def is_pending(self, task_id: TaskID) -> bool:
        with self._lock:
            return task_id in self.pending

    def num_pending(self) -> int:
        with self._lock:
            return len(self.pending)

    def cancel(self, task_id: TaskID) -> Optional[TaskSpec]:
        """Mark a pending task cancelled; its returns resolve to
        TaskCancelledError and any late reply is discarded. Returns the
        spec if the task was still pending, else None."""
        with self._lock:
            pending = self.pending.pop(task_id, None)
            if pending is None:
                return None
            self.cancelled.add(task_id)
            spec = pending.spec
        from .errors import TaskCancelledError
        err = TaskCancelledError(task_id.hex()[:16])
        for oid in spec.return_ids():
            self._cw.memory_store.put(oid, err, is_exception=True)
        self._release_deps(pending)
        return spec

    def is_cancelled(self, task_id: TaskID) -> bool:
        # Lock-free read: set membership is atomic under the GIL and
        # cancellation racing a submit is resolved by the cancel path's
        # own tombstone protocol — taking the lock here cost ~2us on
        # every hot-path submit for a almost-always-False check.
        return task_id in self.cancelled

    def _take_cancelled(self, task_id: TaskID) -> bool:
        if not self.cancelled:
            # Lock-free steady state: the cancelled set is almost always
            # empty and reading it is GIL-atomic — this runs once per
            # completion (plus once per submit), so skipping the lock
            # saves two acquisitions per task on call floods.
            return False
        with self._lock:
            if task_id in self.cancelled:
                self.cancelled.discard(task_id)
                return True
            return False

    def on_completed(self, spec: TaskSpec, reply: Dict[str, Any]):
        if self._take_cancelled(spec.task_id):
            return  # late reply for a cancelled task: returns already failed
        self._cw.task_events.record(spec, "FINISHED")
        # Returns land in the memory store BEFORE the task leaves the
        # pending table: a concurrent get() observing not-pending +
        # not-in-store concludes the result was LOST and spuriously
        # reconstructs (deleting/resubmitting a task that just finished).
        returns = reply.get("returns", [])
        sizes: List[Tuple[ObjectID, int]] = []
        for i, ret in enumerate(returns):
            oid = ObjectID.for_task_return(spec.task_id, ret.get("index", i))
            if ret.get("plasma"):
                sizes.append((oid, ret.get("size", 0)))
                self._cw.reference_counter.mark_in_plasma(oid)
                self._cw.memory_store.put(oid, None, in_plasma=True)
            elif ret.get("refs"):
                # Contains ObjectRefs: deserialize now so borrows register
                # inside the sender's transit-pin window.
                sizes.append((oid, len(ret["data"])))
                value = serialization.deserialize(ret["data"])
                self._cw.memory_store.put(oid, value)
            else:
                # Defer deserialization to the consuming thread (off the
                # io loop; parallel across getters).
                sizes.append((oid, len(ret["data"])))
                self._cw.memory_store.put_raw(oid, ret["data"])
        self._cw.reference_counter.set_sizes(sizes)
        num_dynamic = reply.get("num_dynamic")
        if num_dynamic is not None:
            # Generator task: materialize the handle at index 0, owning
            # every item ref (lineage points at the creating task).
            from .object_ref import ObjectRefGenerator
            item_refs = []
            for i in range(1, num_dynamic + 1):
                oid = ObjectID.for_task_return(spec.task_id, i)
                self._cw.reference_counter.add_owned(
                    oid, lineage_task=spec.task_id)
                item_refs.append(ObjectRef(oid, self._cw.rpc_address))
            self._cw.memory_store.put(
                ObjectID.for_task_return(spec.task_id, 0),
                ObjectRefGenerator(refs=item_refs))
        with self._lock:
            pending = self.pending.pop(spec.task_id, None)
            # Retain lineage so lost plasma returns can be reconstructed.
            if spec.task_type == NORMAL_TASK and spec.max_retries != 0:
                self.lineage[spec.task_id] = spec
                self._lineage_bytes += 256  # spec bookkeeping estimate
                if self._lineage_bytes > CONFIG.max_lineage_bytes:
                    # Evict oldest lineage entries.
                    while self._lineage_bytes > CONFIG.max_lineage_bytes // 2 \
                            and self.lineage:
                        self.lineage.pop(next(iter(self.lineage)))
                        self._lineage_bytes -= 256
        self._release_deps(pending)

    def on_failed(self, spec: TaskSpec, error: Exception,
                  is_application_error: bool) -> bool:
        """Returns True if the task will be retried."""
        if self._take_cancelled(spec.task_id):
            return False  # cancelled: no retry, returns already failed
        with self._lock:
            pending = self.pending.get(spec.task_id)
            if pending is None:
                return False
            retryable = pending.retries_left != 0
            if is_application_error:
                retry_exc = spec.retry_exceptions
                if retry_exc is False or retry_exc is None:
                    retryable = False
                elif isinstance(retry_exc, (list, tuple)):
                    cause = getattr(error, "cause", error)
                    retryable = retryable and isinstance(
                        cause, tuple(retry_exc))
            if retryable:
                pending.retries_left -= 1
                pending.spec.attempt_number += 1
        if retryable:
            logger.info("retrying task %s (%s), attempt %d",
                        spec.name or spec.function.qualname,
                        spec.task_id.hex()[:12], spec.attempt_number)
            # Routed resubmit: the retry re-enters the shard that owns
            # this task/actor (same id -> same shard, so the retry joins
            # the original's loop-confined state).
            self._cw.route_submit(spec)
            return True
        with self._lock:
            pending = self.pending.pop(spec.task_id, None)
        if not isinstance(error, TaskError):
            error = TaskError(spec.function.display_name(),
                              "".join(traceback.format_exception(error)),
                              cause=error)
        self._cw.task_events.record(spec, "FAILED",
                                    error=str(error)[:500])
        for oid in spec.return_ids():
            self._cw.memory_store.put(oid, error, is_exception=True)
        self._release_deps(pending)
        return False

    def _release_deps(self, pending: Optional[PendingTask]):
        if pending is None:
            return
        if pending.dep_ids or pending.contained_ids:
            self._cw.reference_counter.remove_submitted(
                pending.dep_ids + pending.contained_ids)

    def lineage_spec(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._lock:
            return self.lineage.get(task_id)


class ShardedTaskManager:
    """Owner-sharded pending/lineage tables: N TaskManager slices keyed
    by task-id hash (the reference's in-flight task state partitions the
    same way inside its multithreaded core worker). Thread-safe like the
    single slice; every operation routes by the task id it concerns, so
    a task's whole lifecycle — add_pending, cancel tombstones, the
    completion fold — stays on one slice/lock. Constructed only for
    shard counts > 1 (``RTPU_OWNER_SHARDS=1`` keeps the plain
    TaskManager: exact-legacy A/B path)."""

    def __init__(self, core_worker: "CoreWorker", count: int):
        self._count = count
        self._slices = [TaskManager(core_worker) for _ in range(count)]

    def _for(self, task_id: TaskID) -> TaskManager:
        return self._slices[route_bytes(task_id.binary(), self._count)]

    def add_pending(self, spec: TaskSpec,
                    dep_ids: Optional[List[ObjectID]] = None,
                    contained_ids: Optional[List[ObjectID]] = None):
        self._for(spec.task_id).add_pending(spec, dep_ids, contained_ids)

    def is_pending(self, task_id: TaskID) -> bool:
        return self._for(task_id).is_pending(task_id)

    def num_pending(self) -> int:
        return sum(s.num_pending() for s in self._slices)

    def cancel(self, task_id: TaskID) -> Optional[TaskSpec]:
        return self._for(task_id).cancel(task_id)

    def is_cancelled(self, task_id: TaskID) -> bool:
        return self._for(task_id).is_cancelled(task_id)

    def _take_cancelled(self, task_id: TaskID) -> bool:
        return self._for(task_id)._take_cancelled(task_id)

    def on_completed(self, spec: TaskSpec, reply: Dict[str, Any]):
        self._for(spec.task_id).on_completed(spec, reply)

    def on_failed(self, spec: TaskSpec, error: Exception,
                  is_application_error: bool) -> bool:
        return self._for(spec.task_id).on_failed(spec, error,
                                                 is_application_error)

    def lineage_spec(self, task_id: TaskID) -> Optional[TaskSpec]:
        return self._for(task_id).lineage_spec(task_id)


# ---------------------------------------------------------------------------
# Lease management for normal tasks
# (reference: src/ray/core_worker/task_submission/normal_task_submitter.cc)
# ---------------------------------------------------------------------------

@dataclass
class Lease:
    lease_id: int
    worker_address: Address
    worker_id: bytes
    raylet_address: Address
    node_id: str
    last_used: float = field(default_factory=time.monotonic)
    # Pipelined pushes currently outstanding on this leased worker
    # (reference: normal_task_submitter.h max_tasks_in_flight_per_worker —
    # the worker executes serially; pipelining hides push/reply latency).
    inflight: int = 0
    # Set by _drop_lease: other pipelined tasks finishing on this lease
    # must not recycle it back into the idle pool.
    dead: bool = False
    # The (possibly spread-salted) pool key this lease was acquired
    # under. Return/drop MUST use it — returning under a different key
    # would park one lease in two idle lists and break the
    # one-list-per-lease invariant the cleaner relies on.
    key: Optional[Tuple] = None
    granted_at: float = field(default_factory=time.monotonic)
    # Fairness rotation: an overheld lease stops taking new tasks and
    # returns to the raylet once its pipeline drains.
    retiring: bool = False


@dataclass
class _ProbeState:
    push: "asyncio.Future"
    worker: Any
    spec: TaskSpec
    lease: "Lease"
    started: float
    unknown: int = 0
    unreachable: int = 0
    running: int = 0
    recovered: Optional[Dict[str, Any]] = None  # reply fetched via probe
    crashed: Optional[str] = None               # verdict: worker lost it


class NormalTaskSubmitter:
    """One instance per owner shard: every table below is loop-confined
    to the shard's io loop (``# shard-local`` — rtpulint L007 flags
    cross-object reads that lack a ``# cross-shard ok:`` justification).
    Tasks reach their shard via the mailbox (`shard.post`), never by a
    foreign thread touching these dicts."""

    def __init__(self, core_worker: "CoreWorker", shard: OwnerShard):
        self._cw = core_worker
        self._shard = shard
        self._idle: Dict[Tuple, List[Lease]] = {}  # shard-local
        self._running: Dict[TaskID, Lease] = {}  # shard-local
        self._waiters: Dict[Tuple, collections.deque] = {}  # shard-local
        self._inflight_requests: Dict[Tuple, int] = {}  # shard-local
        self._shape_specs: Dict[Tuple, TaskSpec] = {}  # shard-local
        # Pre-encoded lease-request meta per shape: the raylet receives
        # an opaque blob it decodes once per request; spillback hops
        # resend the same bytes without re-encoding.
        self._meta_blobs: Dict[Tuple, bytes] = {}  # shard-local
        self._request_tasks: set = set()  # shard-local
        self._cleaner_started = False
        self._probed: Dict[TaskID, _ProbeState] = {}  # shard-local
        self._probe_sweeper_on = False

    async def cancel_pending_requests(self):
        """Cancel lease requests still queued at raylets (shutdown path)."""
        for task in list(self._request_tasks):
            task.cancel()

    def submit(self, spec: TaskSpec):
        self._shard.post(self._submit(spec))

    def resubmit(self, spec: TaskSpec):
        self.submit(spec)

    async def _submit(self, spec: TaskSpec):
        # Early-return paths consume the cancelled mark: no push means no
        # reply will ever arrive to consume it.
        if self._cw.task_manager._take_cancelled(spec.task_id):
            return
        try:
            await self._resolve_dependencies(spec)
            # timed AFTER dependency resolution: the histogram measures
            # scheduling latency (queueing + raylet round trips), not
            # however long an upstream task takes to produce its result
            submit_t = time.monotonic()
            lease = await self._acquire_lease(spec)
        except Exception as e:
            self._cw.task_manager.on_failed(spec, e, is_application_error=False)
            return
        if lease is None:
            return  # cancelled while queued; returns already resolved
        from .runtime_metrics import runtime_metrics
        metrics = runtime_metrics()
        metrics.lease_wait.observe(time.monotonic() - submit_t)
        metrics.pending_tasks.set(self._cw.task_manager.num_pending(),
                                  tags={"pid": str(os.getpid())})
        self._cw.task_events.record(spec, "LEASED", node_id=lease.node_id)
        if self._cw.task_manager._take_cancelled(spec.task_id):
            self._return_lease(lease.key, lease)
            return
        worker = self._shard.clients.get(lease.worker_address)
        self._running[spec.task_id] = lease
        push_t = time.monotonic()
        try:
            # No deadline on execution itself (tasks run arbitrarily
            # long), but a LOST push/reply must not pin lease.inflight
            # forever (leaks the raylet CPU — observed under 4-driver
            # floods): probe the worker periodically; if it doesn't know
            # the task repeatedly, the push or its reply vanished.
            reply = await self._push_with_probe(worker, spec, lease)
            if reply.get("need_template"):
                # Receiver lost the announced template (fresh process on
                # a reused address / registry pressure): re-announce
                # inline and push again.
                self._shard.tmpl_sent.discard(
                    (lease.worker_address, spec.flat_template.tid))
                reply = await self._push_with_probe(worker, spec, lease)
        except Exception as e:
            # Worker died or became unreachable — a system failure.
            self._drop_lease(lease)
            if isinstance(e, WorkerCrashedError):
                # probe verdict: already carries the postmortem
                err = e
            else:
                err = WorkerCrashedError(
                    f"worker {lease.worker_address} failed: {e}",
                    postmortem=await self._cw.fetch_worker_postmortem(
                        lease.worker_id))
            self._cw.task_manager.on_failed(
                spec, err, is_application_error=False)
            return
        finally:
            self._running.pop(spec.task_id, None)
        metrics.push_roundtrip.observe(time.monotonic() - push_t)
        self._return_lease(lease.key, lease)
        error = reply.get("error")
        if error is not None:
            self._cw.task_manager.on_failed(
                spec, error, is_application_error=True)
        else:
            self._cw.task_manager.on_completed(spec, reply)
        # refresh at completion too, or an idle driver's gauge freezes
        # at the last lease-time reading (>= 1) forever
        metrics.pending_tasks.set(self._cw.task_manager.num_pending(),
                                  tags={"pid": str(os.getpid())})

    async def _push_with_probe(self, worker, spec: TaskSpec,
                               lease: Lease) -> Dict[str, Any]:
        """push_task with liveness probing instead of a duration bound
        (reference: lease liveness is connection-tied in the raylet; here
        the probe asks the worker whether it still knows the task).

        The probing itself runs in ONE sweeper over all outstanding
        pushes: a per-task `asyncio.wait(timeout=...)` costs a
        TimerHandle + wait bookkeeping per call, which dominated the
        1M-queued-task profile. The hot path is a plain await; the
        sweeper resolves stuck pushes by cancelling them after stashing
        a verdict in `_ProbeState`."""
        tmpl = spec.flat_template
        if tmpl is not None and not task_spec_codec.delta_encodable(spec):
            tmpl = None  # oversized args: pickle path handles any size
        if tmpl is not None:
            # Flat wire path: one raw frame (no pickler) — the template
            # is announced once per destination, every push after ships
            # only the struct-packed delta.
            tmpl_data = None
            sent = self._shard.tmpl_sent
            sent_key = (lease.worker_address, tmpl.tid)
            if sent_key not in sent:
                if len(sent) > 8192:
                    sent.clear()  # bound vs worker churn; re-announce heals
                sent.add(sent_key)
                tmpl_data = tmpl.data
            payload = _pack_push_task(
                tmpl.tid, lease.lease_id, tmpl_data,
                task_spec_codec.encode_delta(spec, tmpl.method_name))
            from .runtime_metrics import runtime_metrics
            runtime_metrics().wire_task_bytes.inc(len(payload))
            push = asyncio.ensure_future(worker.call_raw(
                "push_task", payload, timeout=None))
        else:
            push = asyncio.ensure_future(worker.call(
                "push_task", spec=spec, lease_id=lease.lease_id,
                timeout=None))
        ps = _ProbeState(push=push, worker=worker, spec=spec, lease=lease,
                         started=time.monotonic())
        self._probed[spec.task_id] = ps
        if not self._probe_sweeper_on:
            self._probe_sweeper_on = True
            aio.spawn(self._probe_sweeper(), what="probe_sweeper")
        try:
            return await push
        except asyncio.CancelledError:
            # the sweeper cancelled us with a verdict
            if ps.recovered is not None:
                from .runtime_metrics import runtime_metrics
                runtime_metrics().push_recovered.inc()
                return ps.recovered
            if ps.crashed is not None:
                # inner push future was cancelled (not this coroutine) —
                # awaiting the postmortem fetch here is safe
                raise WorkerCrashedError(
                    ps.crashed,
                    postmortem=await self._cw.fetch_worker_postmortem(
                        ps.lease.worker_id)) from None
            raise
        finally:
            self._probed.pop(spec.task_id, None)

    async def _probe_sweeper(self):
        """One loop probing ALL outstanding pushes older than a probe
        period (replaces per-task probe loops)."""
        period = CONFIG.push_probe_period_s
        while True:
            await asyncio.sleep(period)
            if not self._probed:
                self._probe_sweeper_on = False
                return
            now = time.monotonic()
            due = [ps for ps in self._probed.values()
                   if not ps.push.done() and now - ps.started >= period]
            if due:
                # concurrent: K stuck workers must not serialize into
                # K x 15s sweeps
                await asyncio.gather(
                    *(self._probe_one(ps) for ps in due),
                    return_exceptions=True)

    async def _probe_one(self, ps: "_ProbeState"):
        spec, lease = ps.spec, ps.lease
        try:
            state = await ps.worker.call(
                "task_probe", task_hex=spec.task_id.hex(),
                attempt=spec.attempt_number, timeout=15)
        except Exception:
            # Probe timeout / transport error: the worker may just be
            # congested (single-core multi-driver floods). A dead
            # worker's push fails with its own connection error first,
            # so give these a separate, much larger budget instead of
            # counting them as "worker lost the task".
            ps.unreachable += 1
            if ps.unreachable >= CONFIG.push_probe_unreachable_threshold:
                ps.crashed = (
                    f"worker {lease.worker_address} unreachable for "
                    f"{ps.unreachable} probes on task "
                    f"{spec.task_id.hex()[:12]}")
                ps.push.cancel()
            return
        ps.unreachable = 0
        if ps.push.done():
            return  # reply landed while we probed
        if isinstance(state, dict) and state.get("state") == "done":
            # The task finished but its reply frame was lost en route:
            # recover the cached reply via the probe channel instead of
            # dropping the lease and re-executing.
            ps.recovered = state["reply"]
            ps.push.cancel()
            return
        if state == "running":
            ps.unknown = 0
            ps.running += 1
            if ps.running == 6:
                # "running" for ~90s on a tiny task: capture the
                # worker's stacks for postmortem (file survives the
                # processes)
                try:
                    await ps.worker.call(
                        "dump_stacks",
                        path=f"/tmp/rtpu-stuck-{spec.task_id.hex()[:8]}"
                             ".txt",
                        timeout=15)
                except Exception:  # noqa: BLE001
                    logger.debug("postmortem dump_stacks on %s failed",
                                 lease.worker_address, exc_info=True)
            return
        ps.unknown += 1
        if ps.unknown >= CONFIG.push_probe_unknown_threshold:
            ps.crashed = (
                f"worker {lease.worker_address} lost task "
                f"{spec.task_id.hex()[:12]} (probe: {state})")
            ps.push.cancel()

    async def _resolve_dependencies(self, spec: TaskSpec):
        """Wait until owned args exist; inline small plain values
        (reference: DependencyResolver)."""
        for i, arg in enumerate(spec.args):
            if not arg.is_ref:
                continue
            oid = arg.object_id
            if self._cw.reference_counter.is_owner(oid) or \
                    self._cw.task_manager.is_pending(oid.task_id()):
                while not self._cw.memory_store.contains(oid):
                    if not self._cw.task_manager.is_pending(oid.task_id()) \
                            and not self._cw.memory_store.contains(oid):
                        # Owned put object already in plasma: ready.
                        break
                    await self._cw.memory_store.wait_ready_async(oid)
                entry = self._cw.memory_store.get_entry(oid)
                if entry is not None and entry.is_exception:
                    raise entry.value if isinstance(entry.value, Exception) \
                        else TaskError(spec.function.display_name(),
                                       str(entry.value))
                if entry is not None and not entry.in_plasma:
                    if entry.raw is not None:
                        # Ref-free serialized reply: inline the bytes as-is.
                        raw = entry.raw
                        if raw is not None and \
                                len(raw) <= CONFIG.inline_arg_max_bytes:
                            spec.args[i] = TaskArg(is_ref=False, data=raw)
                        continue
                    sobj = serialization.serialize(entry.value)
                    if sobj.total_bytes() <= CONFIG.inline_arg_max_bytes \
                            and not sobj.contained_refs:
                        spec.args[i] = TaskArg(is_ref=False,
                                               data=sobj.to_bytes())

    async def _acquire_lease(self, spec: TaskSpec) -> Optional[Lease]:
        """Lease pipelining (reference: normal_task_submitter.cc — one
        pool of leased workers per task shape, pending tasks queue on it).

        A burst of N submissions must NOT translate into N independent
        raylet round-trips each waiting for its own grant: finished tasks
        hand their lease directly to the next waiter, and extra raylet
        requests are issued only while waiters outnumber grants in
        flight. Without the handoff, returned leases sit idle (resources
        still charged at the raylet) while queued requests starve."""
        key = spec.shape_key()
        # one representative spec per shape: re-issuing lease requests
        # after a fairness rotation needs one. STRIPPED of args — keys
        # are long-lived and a full spec would pin up to
        # inline_arg_max_bytes of payload per distinct shape forever.
        # (Stored once: a dataclasses.replace per submit cost ~8us on
        # the 1M-queued-task path.)
        if key not in self._shape_specs:
            self._shape_specs[key] = dataclasses.replace(spec, args=[])
        if spec.scheduling_strategy.kind == "SPREAD":
            # SPREAD must not pipeline onto a cached lease — each task
            # goes through its own lease request so the raylet's
            # round-robin redirect actually lands tasks on distinct
            # nodes (reference: spread policy is per lease request).
            self._spread_salt = getattr(self, "_spread_salt", 0) + 1
            key = key + (_SPREAD, self._spread_salt)
        idle = self._idle.get(key)
        if idle:
            # Least-loaded lease first so bursts spread across workers
            # before pipelining deepens any one queue.
            lease = min(idle, key=lambda l: l.inflight)
            lease.inflight += 1
            if lease.inflight >= CONFIG.max_tasks_in_flight_per_lease:
                idle.remove(lease)
            return lease
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(key, collections.deque()).append(
            (spec.task_id, fut))
        self._maybe_request_lease(key, spec)
        return await fut

    def _maybe_request_lease(self, key: Tuple, spec: TaskSpec):
        # Bounded pipelining (reference: maximum_pending_lease_requests):
        # beyond the cap, demand is served by lease handoff from finishing
        # tasks; unbounded requests would make the raylet's queue pump
        # quadratic in burst size.
        waiting = len(self._waiters.get(key, ()))
        inflight = self._inflight_requests.get(key, 0)
        if inflight < min(waiting, CONFIG.max_pending_lease_requests_per_shape):
            self._inflight_requests[key] = inflight + 1
            task = asyncio.ensure_future(self._request_lease(key, spec))
            self._request_tasks.add(task)
            task.add_done_callback(self._request_tasks.discard)

    async def _request_lease(self, key: Tuple, spec: TaskSpec):
        try:
            lease = await self._request_new_lease_reclaiming(spec)
        except Exception as e:  # noqa: BLE001 — delivered to one waiter
            self._inflight_requests[key] -= 1
            waiters = self._waiters.get(key)
            while waiters:
                _tid, fut = waiters.popleft()
                if not fut.done():
                    fut.set_exception(e)
                    break
            self._maybe_request_lease(key, spec)
            return
        self._inflight_requests[key] -= 1
        if lease is None:
            # Request dropped at the raylet (cancel_lease_by_task on the
            # tagging task). Reap that task's own waiter so the pool
            # doesn't count it as live demand — otherwise we'd re-issue a
            # replacement request (cold-starting a worker) for a task
            # that will never run.
            waiters = self._waiters.get(key)
            if waiters:
                for entry in list(waiters):
                    tid, fut = entry
                    if tid == spec.task_id:
                        waiters.remove(entry)
                        if not fut.done():
                            fut.set_result(None)
                        break
            self._maybe_request_lease(key, spec)
            return
        self._deliver_lease(key, lease)
        self._maybe_request_lease(key, spec)

    def _deliver_lease(self, key: Tuple, lease: Lease):
        """Hand the lease's free pipeline slots to waiters; park whatever
        capacity remains on the idle list (invariant: `_idle[key]` holds
        exactly the leases with spare capacity, no duplicates)."""
        lease.key = key
        cap = CONFIG.max_tasks_in_flight_per_lease
        waiters = self._waiters.get(key)
        while waiters and lease.inflight < cap:
            _tid, fut = waiters.popleft()
            if fut.done():
                continue
            lease.inflight += 1
            fut.set_result(lease)
        lease.last_used = time.monotonic()
        idle = self._idle.setdefault(key, [])
        if lease.inflight < cap:
            if lease not in idle:
                idle.append(lease)
        elif lease in idle:
            idle.remove(lease)

    async def _request_new_lease_reclaiming(self,
                                            spec: TaskSpec
                                            ) -> Optional[Lease]:
        """Grant-time reclaim of cross-shard idle leases (ROADMAP item 6
        follow-up): with the owner core sharded, every raylet worker can
        be pinned by OTHER shards' idle leases — this shard's request
        then queues at the raylet until some holder's idle-lease cleaner
        tick (lease_idle_timeout_s = 2s) returns a worker, observed as
        ~2s sync-get outliers at RTPU_OWNER_SHARDS>=2. If the grant
        hasn't landed within lease_reclaim_delay_s, ask every other
        shard to return its idle leases (zero in-flight, no local
        waiters) NOW; the raylet's release pump then grants our queued
        request. Single-shard processes skip the watchdog entirely —
        the shards=1 arm stays exact-legacy.

        The watchdog can false-positive on a legitimately slow grant
        (cold worker spawn takes >> the delay even with free
        capacity). That trade is deliberate and cheap: a reclaimed
        worker goes back to the RAYLET's warm idle pool (return
        without dispose — the process is not killed), so the holder
        shard's next task pays one extra lease round trip, not a
        spawn; and the reclaim fires at most once per grant attempt."""
        if len(self._cw.shards) <= 1:
            return await self._request_new_lease(spec)
        grant = asyncio.ensure_future(self._request_new_lease(spec))
        try:
            return await asyncio.wait_for(
                asyncio.shield(grant), CONFIG.lease_reclaim_delay_s)
        except asyncio.TimeoutError:
            self._cw.reclaim_idle_leases(exclude=self._shard)
        except asyncio.CancelledError:
            grant.cancel()
            raise
        try:
            return await grant
        except asyncio.CancelledError:
            grant.cancel()
            raise

    async def reclaim_idle_now(self):
        """Posted onto THIS shard's loop by a peer shard whose lease
        request is starving (see _request_new_lease_reclaiming): the
        idle-lease cleaner's return path without the idle-timeout wait.
        Leases with queued local waiters or in-flight pipelined tasks
        keep their warmth — reclaim must not trade this shard's latency
        for another's."""
        from .runtime_metrics import runtime_metrics
        for key, leases in list(self._idle.items()):
            if self._waiters.get(key):
                continue
            keep = []
            for lease in leases:
                if lease.inflight == 0:
                    lease.dead = True
                    self._shard.fire_and_forget(
                        lease.raylet_address, "return_worker",
                        _retries=CONFIG.rpc_max_retries,
                        lease_id=lease.lease_id)
                    runtime_metrics().lease_reclaims.inc()
                else:
                    keep.append(lease)
            if keep:
                self._idle[key] = keep
            else:
                self._idle.pop(key, None)

    async def _request_new_lease(self, spec: TaskSpec) -> Optional[Lease]:
        shape = spec.shape_key()
        blob = self._meta_blobs.get(shape)
        if blob is None:
            meta = {
                "resources": spec.resources,
                "shape_key": shape,
                "runtime_env": spec.runtime_env,
                "label_selector": spec.label_selector or None,
            }
            strategy = spec.scheduling_strategy
            if strategy.kind == "placement_group":
                meta["pg"] = (strategy.placement_group_id,
                              strategy.bundle_index)
            # Strict dumps (not bare pickle): runtime_env is user data,
            # and the blob encodes once per shape anyway.
            blob = serialization.dumps(meta)
            if len(self._meta_blobs) > 512:
                self._meta_blobs.clear()
            self._meta_blobs[shape] = blob
        strategy = spec.scheduling_strategy
        # SPREAD rides as a per-request overlay (not in the blob): the
        # raylet round-robins SPREAD leases across the cluster view
        # instead of granting locally (reference:
        # scheduling/policy/spread_scheduling_policy)
        spread = strategy.kind == "SPREAD"
        local_addr = self._cw.raylet_address
        raylet_addr = local_addr
        affinity_addr = None
        hard_affinity = False
        if strategy.kind == "node_affinity" and strategy.node_id:
            addr = await self._cw.node_address(strategy.node_id)
            if addr is not None:
                raylet_addr = affinity_addr = addr
                hard_affinity = not strategy.soft
        # Spillback hops stay bounded (16); rejection retries ride a
        # jittered backoff instead of counting as hops — a node under
        # memory pressure or mid-drain legitimately rejects for longer
        # than 16 * 50ms, and the request's semantics are "queue until
        # grantable", not "fail after 0.8s".
        spill_hops = 0
        bo = None
        while True:
            raylet = self._shard.clients.get(raylet_addr)
            try:
                reply = await raylet.call("request_worker_lease",
                                          meta_blob=blob,
                                          task_hex=spec.task_id.hex(),
                                          job=spec.job_id.hex(),
                                          strategy="SPREAD" if spread
                                          else None,
                                          timeout=None,
                                          retries=CONFIG.rpc_max_retries)
            except (asyncio.CancelledError, GeneratorExit):
                raise
            except Exception:
                # A REMOTE raylet died under us (rolling restart /
                # node failure): fall back to the local raylet, which
                # re-spills onto a live node once the view updates.
                # HARD node-affinity targets and the local raylet
                # itself keep the old fail-fast contract; soft
                # affinity prefers running elsewhere over failing.
                if tuple(raylet_addr) == tuple(local_addr) or \
                        (hard_affinity
                         and tuple(raylet_addr) == tuple(affinity_addr)):
                    raise
                logger.warning(
                    "lease request to raylet %s failed; retrying via "
                    "the local raylet", raylet_addr, exc_info=True)
                raylet_addr = local_addr
                spill_hops = 0
                continue
            if reply.get("canceled"):
                return None  # dropped at the raylet; caller re-issues
            if reply.get("spillback_to"):
                spill_hops += 1
                if spill_hops > 16:
                    raise RayTpuError(
                        "could not acquire a worker lease (too many "
                        "spillback hops)")
                raylet_addr = tuple(reply["spillback_to"][1])
                # A SPREAD redirect already chose the node: the target
                # must grant/queue locally, not re-spread (ping-pong).
                spread = False
                continue
            if reply.get("rejected"):
                if reply.get("permanent"):
                    raise RayTpuError(
                        f"worker environment failed: {reply.get('error')}")
                if reply.get("draining") and hard_affinity and \
                        tuple(raylet_addr) == tuple(affinity_addr):
                    # HARD affinity to a draining node: silently
                    # re-routing elsewhere would violate the pin — fail
                    # loudly instead (soft affinity re-routes below).
                    raise RayTpuError(
                        f"node-affinity target "
                        f"{strategy.node_id[:12]} is draining and the "
                        "affinity is hard (soft=False)")
                if bo is None:
                    bo = Backoff(base_s=0.05, max_s=1.0)
                await bo.async_sleep()
                if reply.get("draining") and \
                        tuple(raylet_addr) != tuple(local_addr):
                    # A draining node never grants again — go home and
                    # let the local raylet re-route the request.
                    raylet_addr = local_addr
                    spill_hops = 0
                continue
            if not self._cleaner_started:
                self._cleaner_started = True
                aio.spawn(self._idle_lease_cleaner(),
                          what="idle_lease_cleaner")
            return Lease(
                lease_id=reply["lease_id"],
                worker_address=tuple(reply["worker_address"]),
                worker_id=reply["worker_id"],
                raylet_address=raylet_addr,
                node_id=reply["node_id"])

    def _return_lease(self, key: Tuple, lease: Lease):
        lease.inflight -= 1
        if lease.dead:
            return
        if _is_spread_key(key):
            # One-shot SPREAD lease: never recycled driver-side (reuse
            # would undo the round-robin placement) — the lease returns
            # to its raylet (worker stays in the raylet's idle pool) and
            # the salted per-task key's bookkeeping is reaped so a
            # long-running driver's _waiters/_inflight_requests don't
            # grow with task count.
            if lease.inflight <= 0:
                lease.dead = True
                self._shard.fire_and_forget(lease.raylet_address,
                                            "return_worker",
                                            _retries=CONFIG.rpc_max_retries,
                                            lease_id=lease.lease_id)
                self._idle.pop(key, None)
                self._waiters.pop(key, None)
                self._inflight_requests.pop(key, None)
            return
        if not lease.retiring and \
                time.monotonic() - lease.granted_at > \
                CONFIG.lease_fair_rotation_s:
            # Fairness rotation: an overheld lease stops taking new
            # tasks (under sustained pipelining its in-flight count
            # never reaches 0 otherwise) and goes back to the raylet
            # once drained — the worker stays warm in the raylet's idle
            # pool, and OTHER drivers' queued lease requests get a turn
            # instead of starving behind a flooding driver. Our own
            # queued demand re-requests and joins the raylet's FIFO.
            lease.retiring = True
            idle = self._idle.get(key)
            if idle and lease in idle:
                idle.remove(lease)
        if lease.retiring:
            if lease.inflight <= 0:
                lease.dead = True
                self._shard.fire_and_forget(lease.raylet_address,
                                            "return_worker",
                                            _retries=CONFIG.rpc_max_retries,
                                            lease_id=lease.lease_id)
                if self._waiters.get(key):
                    spec = self._shape_specs.get(key)
                    if spec is not None:
                        self._maybe_request_lease(key, spec)
            return
        self._deliver_lease(key, lease)

    def _drop_lease(self, lease: Lease):
        if lease.dead:
            return
        lease.dead = True
        self._shard.fire_and_forget(lease.raylet_address, "return_worker",
                                    _retries=CONFIG.rpc_max_retries,
                                    lease_id=lease.lease_id, dispose=True)
        # With pipelining a failed lease may still be advertised as having
        # capacity — stop handing it out. The lease lives in at most ONE
        # idle list, the one for its acquisition key.
        leases = self._idle.get(lease.key)
        if leases and lease in leases:
            leases.remove(lease)
        if _is_spread_key(lease.key):
            # unique per-task key: reap the bookkeeping
            if not self._idle.get(lease.key):
                self._idle.pop(lease.key, None)
            if not self._waiters.get(lease.key):
                self._waiters.pop(lease.key, None)
            self._inflight_requests.pop(lease.key, None)

    async def _idle_lease_cleaner(self):
        while True:
            await asyncio.sleep(CONFIG.lease_idle_timeout_s / 2)
            now = time.monotonic()
            for key, leases in list(self._idle.items()):
                keep = []
                for lease in leases:
                    if lease.inflight == 0 and \
                            now - lease.last_used > CONFIG.lease_idle_timeout_s:
                        self._shard.fire_and_forget(
                            lease.raylet_address, "return_worker",
                            _retries=CONFIG.rpc_max_retries,
                            lease_id=lease.lease_id)
                    else:
                        keep.append(lease)
                if keep:
                    self._idle[key] = keep
                else:
                    self._idle.pop(key, None)


# ---------------------------------------------------------------------------
# Actor task submission
# (reference: src/ray/core_worker/task_submission/actor_task_submitter.cc)
# ---------------------------------------------------------------------------

@dataclass
class ActorClientState:
    actor_id: ActorID
    state: str = "PENDING"          # PENDING|ALIVE|RESTARTING|DEAD
    address: Optional[Address] = None
    num_restarts: int = 0
    # GCS scheduling-epoch token: bumps on every (re)schedule of the
    # instance — including budget-free drain migrations, which do NOT
    # move num_restarts. A changed instance means a FRESH process that
    # expects our sequence stream to restart at 0.
    instance: int = 0
    seq: int = 0
    queued: List[TaskSpec] = field(default_factory=list)
    inflight: Dict[int, TaskSpec] = field(default_factory=dict)
    death_cause: str = ""
    reconciling: bool = False
    # One-way push stream: specs accumulated within a loop tick go out as
    # a single push_actor_tasks message.
    sendq: List[TaskSpec] = field(default_factory=list)
    flush_scheduled: bool = False
    # Guards seq/sendq/flush_scheduled across submitting threads and the
    # io loop: steady-state submits run on the CALLER's thread (no per-call
    # coroutine), so the enqueue + seq assignment must be atomic vs the
    # loop-side flush swap (reference: actor_task_submitter.cc holds
    # mu_ across the submit queue the same way).
    lock: threading.Lock = field(default_factory=threading.Lock)
    # Submissions routed through the loop-side slow path that have not yet
    # been assigned a sequence number. While nonzero the fast path must
    # stand down, or a later call could take a lower seq than an earlier
    # one still waiting in the loop queue (ordering violation).
    slow_pending: int = 0
    # In-flight state resolution (subscribe + get_actor_info), shared by
    # every concurrent slow-path submit: one GCS round trip per cold
    # actor, and — critically — waiters resume in FIFO order, so
    # sequence numbers are assigned in SUBMISSION order. Without the
    # coalescing, the first call sat alone behind the pubsub-subscribe
    # await while later calls overtook it and took lower seqs (observed
    # as call 0 executing last on a cold handle).
    resolving: Optional["asyncio.Future"] = None


# read once: os.environ.get costs ~1us and sat on every hot-path submit
_NO_SUBMIT_FASTPATH = bool(CONFIG.no_submit_fastpath)

# -- flat actor-stream framing ----------------------------------------------
# One raw `push_actor_tasks` frame (rpc FLAG_RAW — no pickler on either
# side): done_to address, the templates the receiver hasn't seen yet
# (announce section, parsed BEFORE the deltas that need them), then one
# delta per task.
#   u16 host_len + host utf8 | u32 port
#   u8 n_templates, per: 16s tid | u32 len | template bytes
#   u16 n_frames,   per: 16s tid | u32 len | delta bytes
_AB_U16 = struct.Struct("<H")
_AB_U32 = struct.Struct("<I")
_TID_LEN = task_spec_codec.TEMPLATE_ID_LEN


def _pack_actor_batch(done_to: Address, tmpls, frames) -> bytes:
    host = done_to[0].encode()
    parts = [_AB_U16.pack(len(host)), host, _AB_U32.pack(done_to[1]),
             bytes([len(tmpls)])]
    for tid, data in tmpls:
        parts.append(tid)
        parts.append(_AB_U32.pack(len(data)))
        parts.append(data)
    parts.append(_AB_U16.pack(len(frames)))
    for tid, delta in frames:
        parts.append(tid)
        parts.append(_AB_U32.pack(len(delta)))
        parts.append(delta)
    return b"".join(parts)


# One raw `push_task` frame (normal-task lease push):
#   u8 flags (bit0: template bytes present) | 16s tid | u64 lease_id
#   [u32 len + template bytes] | delta (rest of payload)
_PT_HEAD = struct.Struct("<B16sQ")


def _pack_push_task(tid: bytes, lease_id: int, tmpl_data: Optional[bytes],
                    delta: bytes) -> bytes:
    if tmpl_data is None:
        return _PT_HEAD.pack(0, tid, lease_id) + delta
    return b"".join((_PT_HEAD.pack(1, tid, lease_id),
                     _AB_U32.pack(len(tmpl_data)), tmpl_data, delta))


def _unpack_push_task(payload):
    flags, tid, lease_id = _PT_HEAD.unpack_from(payload, 0)
    off = _PT_HEAD.size
    tmpl_data = None
    if flags & 1:
        (dlen,) = _AB_U32.unpack_from(payload, off)
        off += 4
        tmpl_data = bytes(payload[off:off + dlen])
        off += dlen
    return tid, lease_id, tmpl_data, payload[off:]


def _unpack_actor_batch(payload):
    (hlen,) = _AB_U16.unpack_from(payload, 0)
    off = 2
    host = bytes(payload[off:off + hlen]).decode()
    off += hlen
    (port,) = _AB_U32.unpack_from(payload, off)
    off += 4
    n_tmpls = payload[off]
    off += 1
    tmpls = []
    for _ in range(n_tmpls):
        tid = bytes(payload[off:off + _TID_LEN])
        off += _TID_LEN
        (dlen,) = _AB_U32.unpack_from(payload, off)
        off += 4
        tmpls.append((tid, bytes(payload[off:off + dlen])))
        off += dlen
    (n_frames,) = _AB_U16.unpack_from(payload, off)
    off += 2
    frames = []
    for _ in range(n_frames):
        tid = bytes(payload[off:off + _TID_LEN])
        off += _TID_LEN
        (dlen,) = _AB_U32.unpack_from(payload, off)
        off += 4
        frames.append((tid, payload[off:off + dlen]))
        off += dlen
    return (host, port), tmpls, frames


class ActorTaskSubmitter:
    """Actor task stream (reference: actor_task_submitter.cc PushActorTask).

    Pushes are one-way and batched per loop tick; completions return on a
    batched `actor_tasks_done` stream keyed by task id. The worker orders
    execution by per-caller sequence number (so push reordering is safe)
    and dedups redelivered seqs via its reply cache. Loss of either stream
    is recovered through GCS actor-state pubsub + reconcile polling, which
    resubmits or fails whatever is still marked in flight."""

    def __init__(self, core_worker: "CoreWorker", shard: OwnerShard):
        self._cw = core_worker
        self._shard = shard
        self._actors: Dict[ActorID, ActorClientState] = {}  # shard-local
        # task_id -> (state, spec) for tasks pushed and not yet reported
        self._awaiting: Dict[TaskID, Tuple[ActorClientState, TaskSpec]] = {}  # shard-local
        self._push_time: Dict[TaskID, float] = {}  # shard-local
        self._sweeper_started = False
        self._wire_bytes_acc = 0  # flushed to the counter every ~32KB

    @property
    def _subscribed(self) -> bool:
        # ONE GCS actor-pubsub subscription per process (CoreWorker owns
        # it and fans updates out to the owning shard's mailbox); every
        # shard's fast path keys off the same flag.
        return self._cw._actor_subscribed

    def state_for(self, actor_id: ActorID) -> ActorClientState:
        st = self._actors.get(actor_id)
        if st is None:
            # setdefault: submit() now calls this from arbitrary caller
            # threads, racing the io loop — both must agree on one state
            st = self._actors.setdefault(
                actor_id, ActorClientState(actor_id=actor_id))
        return st

    async def ensure_subscribed(self):
        await self._cw.ensure_actor_subscribed()

    def submit(self, spec: TaskSpec):
        # Fast path: actor known-ALIVE -> enqueue from the caller's thread
        # with no per-call coroutine; one posted flush drains the burst.
        # Anything uncertain (first call, restarting, dead) takes the
        # loop-side slow path which resolves state via the GCS.
        st = self.state_for(spec.actor_id)
        enqueued = need_flush = False
        if (not _NO_SUBMIT_FASTPATH
                and self._subscribed and st.state == "ALIVE"
                and st.address is not None and not st.reconciling
                and not st.queued):
            with st.lock:
                # Re-check under the lock: state transitions drain the
                # queues holding this same lock, so an ALIVE observed here
                # cannot flip mid-enqueue; slow_pending == 0 means no
                # earlier call is still waiting for its seq on the loop.
                if st.state == "ALIVE" and st.address is not None \
                        and not st.queued and st.slow_pending == 0:
                    if self._cw.task_manager.is_cancelled(spec.task_id):
                        spec.method_name = "__rtpu_cancelled__"
                    spec.sequence_number = st.seq
                    st.seq += 1
                    st.inflight[spec.sequence_number] = spec
                    self._awaiting[spec.task_id] = (st, spec)
                    self._push_time[spec.task_id] = time.monotonic()
                    st.sendq.append(spec)
                    enqueued = True
                    need_flush = not st.flush_scheduled
                    if need_flush:
                        st.flush_scheduled = True
        if enqueued:
            if need_flush:
                self._shard.post(self._flush(st))
            return
        with st.lock:
            st.slow_pending += 1
        self._shard.post(self._submit_slow(spec, st))

    async def _submit_slow(self, spec: TaskSpec, st: ActorClientState):
        try:
            await self._submit(spec)
        finally:
            with st.lock:
                st.slow_pending -= 1

    async def _submit(self, spec: TaskSpec):
        st = self.state_for(spec.actor_id)
        if st.state != "ALIVE" or st.address is None:
            await self._resolve_actor(st)
        if st.state == "DEAD":
            self._fail(spec, st.death_cause)
            return
        with st.lock:
            spec.sequence_number = st.seq
            st.seq += 1
            if st.state != "ALIVE":
                st.queued.append(spec)
                return
        await self._push(st, spec)

    async def _resolve_actor(self, st: ActorClientState):
        """Resolve a cold/uncertain actor's state ONCE for all concurrent
        submits (handle may have been deserialized in a process that
        never saw the creation). The resolver subscribes + fetches; every
        other submit awaits the same future and wakes in FIFO order."""
        fut = st.resolving
        if fut is not None:
            await fut
            return
        fut = st.resolving = asyncio.get_running_loop().create_future()
        try:
            await self.ensure_subscribed()
            info = await self._cw.gcs_call("get_actor_info",
                                           actor_id=st.actor_id)
            if info is not None and info["state"] == "ALIVE":
                st.state = "ALIVE"
                st.address = tuple(info["address"])
                st.num_restarts = info.get("num_restarts",
                                           st.num_restarts)
                st.instance = info.get("instance", st.instance)
            elif info is not None and info["state"] == "DEAD":
                st.state = "DEAD"
                st.death_cause = info.get("death_cause", "actor dead")
        finally:
            st.resolving = None
            fut.set_result(None)

    async def _push(self, st: ActorClientState, spec: TaskSpec):
        if self._cw.task_manager.is_cancelled(spec.task_id):
            # Cancelled while queued: the sequence number must still reach
            # the actor (its ordered queues advance per-seq), so push a
            # tombstone the executor completes without running user code.
            spec.method_name = "__rtpu_cancelled__"
        with st.lock:
            st.inflight[spec.sequence_number] = spec
            self._awaiting[spec.task_id] = (st, spec)
            self._push_time[spec.task_id] = time.monotonic()
            st.sendq.append(spec)
            need_flush = not st.flush_scheduled
            if need_flush:
                st.flush_scheduled = True
        if need_flush:
            asyncio.get_running_loop().call_soon(
                lambda: asyncio.ensure_future(self._flush(st)))
        if not self._sweeper_started:
            self._sweeper_started = True
            asyncio.ensure_future(self._straggler_sweep())

    async def _flush(self, st: ActorClientState):
        with st.lock:
            st.flush_scheduled = False
            specs, st.sendq = st.sendq, []
        if not specs:
            return
        if st.state != "ALIVE" or st.address is None:
            # Address lost between enqueue and flush: park in queued; the
            # next ALIVE update re-pushes. Only specs still awaiting are
            # ours to park (an actor-state update may have reclaimed them).
            with st.lock:
                for spec in specs:
                    if self._awaiting.pop(spec.task_id, None) is not None:
                        st.inflight.pop(spec.sequence_number, None)
                        st.queued.append(spec)
            return
        worker = self._shard.clients.get(st.address)
        try:
            await self._send_batch(worker, st.address, specs)
        except Exception:
            with st.lock:
                for spec in specs:
                    if self._awaiting.pop(spec.task_id, None) is not None:
                        st.inflight.pop(spec.sequence_number, None)
                        st.queued.append(spec)
            # Either the actor is dying/restarting (the GCS will publish an
            # update that drains the queue) or this was a transient transport
            # failure with the actor still healthy — reconcile with the GCS
            # rather than parking forever.
            asyncio.ensure_future(self._reconcile(st))

    async def _send_batch(self, worker, address: Address,
                          specs: List[TaskSpec]):
        """Push one flushed batch: template-bearing specs go as one raw
        flat frame (template announce + deltas, no pickler); anything
        without a template rides the legacy pickled stream."""
        frames = []
        tmpls = []
        legacy = []
        sent = self._shard.tmpl_sent
        encode = task_spec_codec.encode_delta
        for spec in specs:
            tmpl = spec.flat_template
            if tmpl is None or not task_spec_codec.delta_encodable(spec):
                legacy.append(spec)
                continue
            key = (address, tmpl.tid)
            if key not in sent:
                if len(sent) > 8192:
                    # Bound against worker churn (dead addresses are
                    # never pruned individually); a clear only costs a
                    # proactive re-announce per live destination.
                    sent.clear()
                if len(tmpls) >= 255:
                    # Announce section is full (u8 count): divert to the
                    # pickled stream rather than knowingly shipping a
                    # delta the receiver cannot decode (which would burn
                    # a retry attempt per task).
                    legacy.append(spec)
                    continue
                sent.add(key)
                tmpls.append((tmpl.tid, tmpl.data))
            frames.append((tmpl.tid, encode(spec, tmpl.method_name)))
        # Chunked: the frame count is u16 on the wire, and a restart
        # re-push can batch an arbitrary backlog in one flush.
        for start in range(0, len(frames), 32768):
            chunk = frames[start:start + 32768]
            payload = _pack_actor_batch(self._shard.rpc_address,
                                        tmpls if start == 0 else [], chunk)
            # Counter inc'd every ~32KB, not per (possibly tiny) batch.
            self._wire_bytes_acc += len(payload)
            if self._wire_bytes_acc >= 32768:
                acc, self._wire_bytes_acc = self._wire_bytes_acc, 0
                from .runtime_metrics import runtime_metrics
                runtime_metrics().wire_task_bytes.inc(acc)
            await worker.oneway_raw("push_actor_tasks", payload)
        if legacy:
            await worker.oneway("push_actor_tasks", specs=legacy,
                                done_to=self._shard.rpc_address)

    def on_done(self, task_id: TaskID, reply: Dict[str, Any]):
        """A completion from the actor's done stream (possibly duplicated
        on redelivery; only the first report wins). `task_id` may be a
        BORROWED key (ids.iter_borrowed) — valid for the pops below but
        never retained; anything that outlives this call uses the
        entry's own spec.task_id."""
        entry = self._awaiting.pop(task_id, None)
        self._push_time.pop(task_id, None)
        if entry is None:
            return
        st, spec = entry
        st.inflight.pop(spec.sequence_number, None)
        sys_err = reply.get("system_error")
        if sys_err is not None:
            # Worker-side infrastructure failure: resend (bounded), the
            # analog of the old request/response path's requeue. A
            # system_error means execute() raised BEFORE consuming the
            # sequence number, so giving up leaves a hole the executor's
            # ordered queue would wait on forever — fill it with a
            # tombstone (same trick as cancellation) after failing.
            if "unknown template" in str(sys_err) and \
                    spec.flat_template is not None:
                # Receiver lost the announced template (fresh process /
                # registry pressure): clear the announce record so the
                # re-push re-includes the template bytes.
                self._shard.tmpl_sent.discard(
                    (st.address, spec.flat_template.tid))
            if spec.attempt_number < 3:
                spec.attempt_number += 1
                aio.spawn(self._push(st, spec), what="actor_task_repush")
            else:
                self._fail(spec, sys_err)
                self._push_untracked_tombstone(st, spec)
            return
        error = reply.get("error")
        if error is not None:
            self._cw.task_manager.on_failed(spec, error,
                                            is_application_error=True)
        else:
            self._cw.task_manager.on_completed(spec, reply)

    async def _straggler_sweep(self):
        """Backstop for lost done-stream messages (the oneway push/done
        frames vanish if a connection drops mid-flight while the actor
        stays ALIVE — no pubsub update will ever fire). Periodically asks
        each actor for the status of long-outstanding tasks; cached
        replies are recovered, never-arrived pushes are resent."""
        while not self._cw._shutdown:
            await asyncio.sleep(10.0)
            try:
                await self._sweep_once(30.0)
            except Exception:
                logger.exception("actor straggler sweep failed")

    async def _sweep_once(self, age_s: float):
        now = time.monotonic()
        stale_by_actor: Dict[ActorID, List[TaskSpec]] = {}
        for task_id, t in list(self._push_time.items()):
            if now - t < age_s:
                continue
            entry = self._awaiting.get(task_id)
            if entry is None:
                self._push_time.pop(task_id, None)
                continue
            st, spec = entry
            if st.state == "ALIVE" and spec.sequence_number in st.inflight:
                stale_by_actor.setdefault(st.actor_id, []).append(spec)
        for actor_id, specs in stale_by_actor.items():
            st = self._actors.get(actor_id)
            if st is None or st.state != "ALIVE" or st.address is None:
                continue
            client = self._shard.clients.get(st.address)
            queries = [(self._cw.worker_id.hex(), s.sequence_number,
                        s.task_id.hex()) for s in specs]
            try:
                statuses = await client.call("actor_task_status",
                                             queries=queries, timeout=30)
            except Exception:
                asyncio.ensure_future(self._reconcile(st))
                continue
            for (task_hex, status, cached), spec in zip(statuses, specs):
                task_id = spec.task_id
                if status == "done":
                    self.on_done(task_id, cached)
                elif status == "running":
                    self._push_time[task_id] = time.monotonic()
                elif status == "unknown":
                    # push never arrived: resend the same seq
                    if self._awaiting.pop(task_id, None) is not None:
                        self._push_time.pop(task_id, None)
                        st.inflight.pop(spec.sequence_number, None)
                        aio.spawn(self._push(st, spec),
                                  what="actor_task_resend")
                else:  # lost: executed but reply evicted — unrecoverable
                    if self._awaiting.pop(task_id, None) is not None:
                        self._push_time.pop(task_id, None)
                        st.inflight.pop(spec.sequence_number, None)
                        self._fail(spec,
                                   "actor task reply lost (cache evicted)")

    def _fail(self, spec: TaskSpec, cause: str):
        err = ActorDiedError(spec.actor_id, cause or "actor died")
        self._cw.task_manager.on_failed(spec, err, is_application_error=False)

    def _push_untracked_tombstone(self, st: ActorClientState,
                                  spec: TaskSpec):
        """Send an abandoned task's sequence number to the actor as a
        no-op so the ordered execution queue advances past it. The task
        itself is already failed locally; the tombstone's done report
        finds no _awaiting entry and is ignored."""
        spec.method_name = "__rtpu_cancelled__"
        with st.lock:
            st.sendq.append(spec)
            need_flush = not st.flush_scheduled
            if need_flush:
                st.flush_scheduled = True
        if need_flush:
            asyncio.get_running_loop().call_soon(
                lambda: asyncio.ensure_future(self._flush(st)))

    async def _reconcile(self, st: ActorClientState):
        """After a failed push, poll the GCS: if the actor is still ALIVE at
        the same incarnation the failure was transient — flush the queue
        ourselves, since no pubsub update will ever arrive."""
        if st.reconciling:
            return
        st.reconciling = True
        try:
            for delay in (0.1, 0.3, 1.0, 2.0, 5.0):
                await asyncio.sleep(delay)
                if not st.queued and not st.inflight:
                    return
                try:
                    info = await self._cw.gcs_call("get_actor_info",
                                                   actor_id=st.actor_id)
                except Exception:
                    logger.debug("get_actor_info during reconcile failed; "
                                 "retrying", exc_info=True)
                    continue
                if info is None:
                    continue
                if info["state"] == "DEAD":
                    await self._on_actor_update({
                        "actor_id": st.actor_id, "state": "DEAD",
                        "death_cause": info.get("death_cause", "")})
                    return
                if info["state"] == "ALIVE":
                    await self._on_actor_update({
                        "actor_id": st.actor_id, "state": "ALIVE",
                        "address": info["address"],
                        "num_restarts": info.get("num_restarts", 0),
                        "instance": info.get("instance", st.instance)})
                    return
                # RESTARTING/PENDING: keep polling as a pubsub backstop.
        finally:
            st.reconciling = False

    def replay_after_gcs_reconnect(self):
        """Runs on this shard's loop after the GCS client re-established
        itself on a new incarnation: pubsub updates published during the
        outage are gone, so every actor with in-flight or parked work
        (or a non-terminal unresolved state) re-reconciles against the
        recovered actor table instead of waiting for the straggler
        sweep's 30s backstop."""
        for st in list(self._actors.values()):
            if st.state == "DEAD":
                continue
            if st.inflight or st.queued or st.state != "ALIVE":
                asyncio.ensure_future(self._reconcile(st))

    async def _on_actor_update(self, message: Dict[str, Any]):
        actor_id = message["actor_id"]
        st = self._actors.get(actor_id)
        if st is None:
            return
        state = message["state"]
        if state == "ALIVE":
            with st.lock:
                restarted = \
                    message.get("num_restarts", 0) != st.num_restarts \
                    or message.get("instance",
                                   st.instance) != st.instance
                st.num_restarts = message.get("num_restarts", 0)
                st.instance = message.get("instance", st.instance)
                st.state = "ALIVE"
                st.address = tuple(message["address"])
                pending = sorted(st.queued + list(st.inflight.values()),
                                 key=lambda s: s.sequence_number)
                st.queued = []
                st.inflight = {}
                st.sendq = []  # unsent specs are in inflight -> pending
                for spec in pending:
                    self._awaiting.pop(spec.task_id, None)
                if restarted:
                    # New actor instance: renumber surviving tasks from 0.
                    st.seq = 0
                    for spec in pending:
                        spec.sequence_number = st.seq
                        st.seq += 1
            for spec in pending:
                aio.spawn(self._push(st, spec), what="actor_task_replay")
        elif state == "RESTARTING":
            with st.lock:
                st.state = "RESTARTING"
                st.address = None
        elif state == "DEAD":
            with st.lock:
                st.state = "DEAD"
                st.death_cause = message.get("death_cause", "actor died")
                pending = st.queued + list(st.inflight.values())
                st.queued = []
                st.inflight = {}
                st.sendq = []
                for spec in pending:
                    self._awaiting.pop(spec.task_id, None)
            for spec in pending:
                self._fail(spec, st.death_cause)


# ---------------------------------------------------------------------------
# Execution (reference: src/ray/core_worker/task_execution/ +
# python/ray/_raylet.pyx task_execution_handler/execute_task)
# ---------------------------------------------------------------------------

def _is_small_result(result) -> bool:
    """Cheap static check for results whose serialization is microseconds
    — packaging those inline beats a thread-pool round trip."""
    if result is None or isinstance(result, (bool, int, float)):
        return True
    if isinstance(result, (str, bytes)):
        return len(result) < 32768
    if isinstance(result, np.ndarray):
        return result.nbytes < 32768
    return False


class _RuntimeContext(threading.local):
    def __init__(self):
        self.task_spec: Optional[TaskSpec] = None
        self.actor_id: Optional[ActorID] = None


RUNTIME_CTX = _RuntimeContext()


_EMPTY_ARGS_CACHE = None
_NONE_DATA_CACHE = None


def _empty_args_data() -> bytes:
    """The driver's constant empty-args bundle bytes (remote_function
    pickles it once; the worker compares against the same constant)."""
    global _EMPTY_ARGS_CACHE
    if _EMPTY_ARGS_CACHE is None:
        from ..remote_function import pack_args
        _EMPTY_ARGS_CACHE = pack_args((), {})[0].data
    return _EMPTY_ARGS_CACHE


def _none_data() -> bytes:
    global _NONE_DATA_CACHE
    if _NONE_DATA_CACHE is None:
        _NONE_DATA_CACHE = serialization.serialize(None).to_bytes()
    return _NONE_DATA_CACHE


def _reply_nbytes(reply: Dict[str, Any]) -> int:
    """Approximate retained size of a push reply (inline return bytes)."""
    total = 64
    for ret in reply.get("returns", ()):
        data = ret.get("data") if isinstance(ret, dict) else None
        if data is not None:
            total += len(data)
    return total


class TaskExecutor:
    def __init__(self, core_worker: "CoreWorker"):
        self._cw = core_worker
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="rtpu-exec")
        self._actor_instance: Any = None
        self._actor_id: Optional[ActorID] = None
        self._actor_pools: Dict[str, concurrent.futures.ThreadPoolExecutor] = {}
        self._actor_async_sem: Optional[asyncio.Semaphore] = None
        self._is_asyncio = False
        # method-name -> iscoroutinefunction (inspect costs ~10us/call)
        self._coro_cache: Dict[str, bool] = {}
        # Ordered execution is per *caller*: each submitting worker numbers
        # its own stream (reference: per-client actor scheduling queues).
        self._next_seq: Dict[bytes, int] = {}
        self._seq_buffer: Dict[bytes,
                               Dict[int, Tuple[TaskSpec, asyncio.Future]]] = {}
        self._reply_cache: Dict[bytes, Dict[int, Dict[str, Any]]] = {}
        # Replies still being computed, keyed like the reply cache: a
        # duplicate push for a running task awaits the original's future.
        self._inflight: Dict[bytes, Dict[int, asyncio.Future]] = {}
        # Cancellation: tasks marked before they start never run; running
        # async actor tasks are asyncio-cancelled (sync tasks cannot be
        # interrupted mid-flight without force-killing the worker).
        self.cancelled_tasks: Set[TaskID] = set()
        self._running_async: Dict[TaskID, asyncio.Task] = {}
        self._running_sync: Set[TaskID] = set()

    def cancel(self, task_id: TaskID):
        self.cancelled_tasks.add(task_id)
        atask = self._running_async.get(task_id)
        if atask is not None:
            atask.cancel()

    def is_running(self, task_id: TaskID) -> bool:
        return task_id in self._running_sync or task_id in self._running_async

    async def execute(self, spec: TaskSpec) -> Dict[str, Any]:
        await self._cw.ensure_job_env(spec.job_id)
        if spec.task_type == ACTOR_TASK:
            return await self._execute_actor_task(spec)
        fut = asyncio.get_running_loop().create_future()
        self._pool.submit(self._run_to_future, spec, fut)
        return await fut

    def _run_to_future(self, spec: TaskSpec, fut: "asyncio.Future"):
        """Pool-thread wrapper: always resolves `fut` on the io loop with a
        batched wakeup (vs run_in_executor's per-task self-pipe write).
        BaseExceptions (sys.exit in user code) must still produce a reply —
        an unset future would hang the caller's push forever."""
        try:
            result = self._run_task(spec)
        except BaseException as e:  # noqa: BLE001 — must answer the RPC
            result = {"error": TaskError(
                spec.function.display_name() or spec.method_name,
                f"task raised {type(e).__name__}: {e}", cause=None)}
        EventLoopThread.get().post_call(
            lambda: fut.set_result(result) if not fut.done() else None)

    async def _execute_actor_task(self, spec: TaskSpec) -> Dict[str, Any]:
        return await asyncio.shield(self.submit_actor_task(spec))

    def submit_actor_task(self, spec: TaskSpec) -> "asyncio.Future":
        """Ordered, dedup'd actor-task submission — plain function (no
        wrapper coroutine/Task per call: the push-stream hot path attaches
        a done-callback to the returned future instead). Must run on the
        io loop. Enforces per-caller submission order by sequence number.
        """
        loop = asyncio.get_running_loop()
        caller = spec.owner_worker_id
        seq = spec.sequence_number
        if seq < self._next_seq.get(caller, 0):
            # Duplicate push (caller lost our reply): serve the cached
            # reply instead of re-executing (at-most-once per seq). A
            # still-running original has no cached reply yet — hand back
            # its future (callers never cancel these).
            cached = self._reply_cache.get(caller, {}).get(seq)
            if cached is not None:
                fut = loop.create_future()
                fut.set_result(cached)
                return fut
            inflight = self._inflight.get(caller, {}).get(seq)
            if inflight is not None:
                return inflight
            fut = loop.create_future()
            fut.set_result({"error": TaskError(
                spec.method_name,
                "duplicate actor task with evicted reply")})
            return fut
        buffered = self._seq_buffer.get(caller, {}).get(seq)
        if buffered is not None:
            # Re-push of a still-buffered seq (caller reconnected before
            # the original dispatched): piggyback on the original future —
            # replacing it would orphan the first handler forever.
            return buffered[1]
        fut = loop.create_future()
        self._seq_buffer.setdefault(caller, {})[seq] = (spec, fut)
        self._inflight.setdefault(caller, {})[seq] = fut

        def _finish(f, caller=caller, seq=seq):
            # Cache the reply the moment it exists — even if the push RPC
            # that started this task was dropped, a retried push must find it.
            self._inflight.get(caller, {}).pop(seq, None)
            if f.cancelled() or f.exception() is not None:
                return
            cache = self._reply_cache.setdefault(caller, {})
            cache[seq] = f.result()
            while len(cache) > 64:
                cache.pop(next(iter(cache)))
        fut.add_done_callback(_finish)
        self._drain_ready(caller)
        return fut

    def _drain_ready(self, caller: bytes):
        buffer = self._seq_buffer.get(caller, {})
        self._next_seq.setdefault(caller, 0)
        while self._next_seq[caller] in buffer:
            spec, fut = buffer.pop(self._next_seq[caller])
            self._next_seq[caller] += 1
            if self._is_asyncio:
                aio.spawn(self._run_async_actor_task(spec, fut),
                          what="async_actor_task")
            else:
                group = spec.concurrency_groups.get("_group") \
                    if spec.concurrency_groups else None
                pool = self._actor_pools.get(group or "_default", self._pool)
                pool.submit(self._run_to_future, spec, fut)

    async def _run_async_actor_task(self, spec: TaskSpec, fut: asyncio.Future):
        self._running_async[spec.task_id] = asyncio.current_task()
        try:
            async with self._actor_async_sem:
                if spec.task_id in self.cancelled_tasks:
                    self.cancelled_tasks.discard(spec.task_id)
                    result = {"cancelled": True}
                else:
                    result = await self._run_task_async(spec)
        except asyncio.CancelledError:
            result = {"cancelled": True}
        finally:
            self._running_async.pop(spec.task_id, None)
            self.cancelled_tasks.discard(spec.task_id)
        if not fut.done():
            fut.set_result(result)

    # -- shared execution helpers ---------------------------------------

    def _load_args(self, spec: TaskSpec) -> Tuple[tuple, dict]:
        data = spec.args[0].data
        if data == _empty_args_data() and len(spec.args) == 1:
            # No-arg calls dominate control floods; the driver pickles
            # this constant bundle once — skip the symmetric unpickle.
            return (), {}
        bundle = serialization.deserialize(data)
        ref_values = []
        for arg in spec.args[1:]:
            if arg.is_ref:
                ref = ObjectRef(arg.object_id, arg.owner_address)
                ref_values.append(self._cw.get([ref])[0])
            else:
                # Resolved ref inlined by the submitter's DependencyResolver.
                ref_values.append(serialization.deserialize(arg.data))

        def subst(v):
            return ref_values[v.index] if isinstance(v, _RefPlaceholder) else v

        return (tuple(subst(a) for a in bundle.args),
                {k: subst(v) for k, v in bundle.kwargs.items()})

    def _package_returns(self, spec: TaskSpec, result: Any) -> Dict[str, Any]:
        if spec.is_generator():
            return self._package_dynamic_returns(spec, result)
        if spec.num_returns == 0:
            return {"returns": []}
        values = (result,) if spec.num_returns == 1 else tuple(result)
        if spec.num_returns > 1 and len(values) != spec.num_returns:
            raise ValueError(
                f"task declared num_returns={spec.num_returns} but returned "
                f"{len(values)} values")
        returns = []
        for index, value in enumerate(values):
            if value is None:
                # None returns dominate control-plane methods; their
                # serialized form is a constant.
                returns.append({"data": _none_data()})
                continue
            sobj = serialization.serialize(value)
            self._cw.reference_counter.pin_for_transit(sobj.contained_refs)
            oid = ObjectID.for_task_return(spec.task_id, index)
            if sobj.total_bytes() > CONFIG.max_direct_call_object_size:
                self._cw.put_serialized_to_plasma(oid, sobj,
                                                 owner=spec.owner_address)
                returns.append({"plasma": True, "size": sobj.total_bytes()})
            else:
                ret = {"data": sobj.to_bytes()}
                if sobj.contained_refs:
                    # Owner must deserialize eagerly so the borrower
                    # registration happens inside the transit-pin window.
                    ret["refs"] = True
                returns.append(ret)
        return {"returns": returns}

    def _package_dynamic_returns(self, spec: TaskSpec,
                                 result: Any) -> Dict[str, Any]:
        """Generator task: each yielded item becomes its own return object
        at index 1..N; index 0 is reserved for the generator handle the
        owner materializes on completion."""
        returns = []
        index = 0
        for value in result:
            index += 1
            sobj = serialization.serialize(value)
            self._cw.reference_counter.pin_for_transit(sobj.contained_refs)
            oid = ObjectID.for_task_return(spec.task_id, index)
            if sobj.total_bytes() > CONFIG.max_direct_call_object_size:
                self._cw.put_serialized_to_plasma(oid, sobj,
                                                  owner=spec.owner_address)
                returns.append({"index": index, "plasma": True,
                                "size": sobj.total_bytes()})
            else:
                ret = {"index": index, "data": sobj.to_bytes()}
                if sobj.contained_refs:
                    ret["refs"] = True  # owner must deserialize eagerly
                returns.append(ret)
        return {"returns": returns, "num_dynamic": index}

    def _run_task(self, spec: TaskSpec) -> Dict[str, Any]:
        if spec.method_name == "__rtpu_cancelled__" \
                or spec.task_id in self.cancelled_tasks:
            self.cancelled_tasks.discard(spec.task_id)
            return {"cancelled": True}
        RUNTIME_CTX.task_spec = spec
        RUNTIME_CTX.actor_id = spec.actor_id
        # Tag this thread for the stack sampler / fleet stack dumps:
        # samples taken while user code runs carry the task identity.
        profiler.note_task(spec)
        # Arm XLA compile tracking the moment jax appears in this
        # worker (an earlier task imported it): listeners must precede
        # the compiles they count, and user code — not ray_tpu — is
        # what imports jax here.
        from . import accel
        accel.maybe_install()
        self._running_sync.add(spec.task_id)
        self._cw.task_events.record(spec, "RUNNING", pid=os.getpid())
        # Continue the caller's trace: user code in this task opening
        # trace_span() nests under the submitting span (reference:
        # tracing_helper extracts the injected context the same way).
        # ALWAYS set — a stale context from the previous task on this
        # thread must not leak into an untraced call.
        from ..util.tracing import set_trace_context
        set_trace_context(tuple(spec.trace_context)
                          if spec.trace_context is not None else None)
        # A traced call gets an execution span of its own: the worker-side
        # child of the submitting span, so get_trace() sees the process
        # hop even when the task body opens no spans itself. Recorded
        # out-of-band — user code still inherits the CALLER's context.
        span_start = time.time() if spec.trace_context is not None \
            else None
        try:
            if spec.task_type == ACTOR_TASK \
                    and spec.method_name == "__rtpu_terminate__":
                return self._graceful_exit(spec)
            if spec.runtime_env:
                self._cw.runtime_env_manager.apply(spec.runtime_env,
                                                   self._cw.gcs)
            packed_args, packed_kwargs = self._load_args(spec)
            if spec.task_type == ACTOR_CREATION_TASK:
                # _actor_id is set BEFORE __init__ runs so the guard
                # covers the whole creation window (a second push
                # arriving mid-__init__ must not slip past).
                if self._actor_id is not None and \
                        self._actor_id != spec.actor_id:
                    # This worker ALREADY hosts a different actor: a
                    # double-granted lease (scheduler bug or a stale
                    # grant racing its release) tried to bind a second
                    # actor here. Silently re-running __init__ would
                    # cross-wire BOTH actors' handles onto one instance
                    # — refuse instead; the scheduler re-places cleanly.
                    raise RuntimeError(
                        f"worker already hosts actor "
                        f"{self._actor_id.hex()}; refusing creation of "
                        f"{spec.actor_id.hex()} (double-granted lease)")
                cls = self._cw.function_manager.load(spec.job_id,
                                                     spec.function)
                self._setup_actor(spec)
                self._actor_id = spec.actor_id
                self._actor_instance = cls(*packed_args, **packed_kwargs)
                return {"returns": []}
            if spec.task_type == ACTOR_TASK:
                if spec.method_name == "__rtpu_dag_exec__":
                    # Compiled-graph exec loop: pin this actor into its
                    # channel-driven schedule (reference: do_exec_tasks).
                    from ..dag.worker_loop import exec_loop
                    result = exec_loop(self._actor_instance, *packed_args)
                else:
                    method = getattr(self._actor_instance, spec.method_name)
                    result = method(*packed_args, **packed_kwargs)
            else:
                func = self._cw.function_manager.load(spec.job_id,
                                                      spec.function)
                result = func(*packed_args, **packed_kwargs)
            return self._package_returns(spec, result)
        except Exception as e:  # noqa: BLE001 — crosses process boundary
            return {"error": TaskError(spec.function.display_name() or
                                       spec.method_name,
                                       traceback.format_exc(), cause=e)}
        finally:
            if span_start is not None:
                from ..util.tracing import record_child_span
                record_child_span(
                    "task:" + (spec.name or spec.method_name
                               or spec.function.display_name()),
                    tuple(spec.trace_context), span_start, time.time(),
                    task_id=spec.task_id.hex())
            RUNTIME_CTX.task_spec = None
            RUNTIME_CTX.actor_id = None
            profiler.clear_task()
            self._running_sync.discard(spec.task_id)
            # A cancel that raced past the start check is moot once the
            # task finishes; drop the mark so the set stays bounded.
            self.cancelled_tasks.discard(spec.task_id)

    def _graceful_exit(self, spec: TaskSpec) -> Dict[str, Any]:
        try:
            self._cw.gcs.call_sync("actor_exited", actor_id=spec.actor_id,
                                   cause="terminate() called", timeout=10)
        except Exception:
            logger.debug("actor_exited notification failed; GCS health "
                         "checks will reap the actor", exc_info=True)
        EventLoopThread.get().loop.call_later(0.1, os._exit, 0)
        return self._package_returns(spec, None)

    def _is_coroutine_method(self, name: str, method) -> bool:
        cached = self._coro_cache.get(name)
        if cached is None:
            import inspect
            cached = inspect.iscoroutinefunction(method)
            self._coro_cache[name] = cached
        return cached

    async def _run_task_async(self, spec: TaskSpec) -> Dict[str, Any]:
        span_start = None
        try:
            if spec.method_name == "__rtpu_cancelled__":
                return {"cancelled": True}
            if spec.method_name == "__rtpu_terminate__":
                return self._graceful_exit(spec)
            from ..util.tracing import set_trace_context
            set_trace_context(tuple(spec.trace_context)
                              if spec.trace_context is not None else None)
            if spec.trace_context is not None:
                span_start = time.time()
            # Small ref-free args deserialize in microseconds — the
            # executor hop costs more than it saves. Offload only when
            # an arg must be fetched (blocking get) or the bundle is big.
            loop = asyncio.get_running_loop()
            if len(spec.args) == 1 and len(spec.args[0].data) < 65536:
                args, kwargs = self._load_args(spec)
            else:
                args, kwargs = await loop.run_in_executor(
                    None, self._load_args, spec)
            self._cw.task_events.record(spec, "RUNNING", pid=os.getpid())
            from . import accel
            accel.maybe_install()  # see _run_task — same task boundary
            method = getattr(self._actor_instance, spec.method_name)
            if self._is_coroutine_method(spec.method_name, method):
                RUNTIME_CTX.task_spec = spec
                RUNTIME_CTX.actor_id = spec.actor_id
                # io-loop attribution is approximate (awaits interleave
                # tasks on one thread) but right whenever user code is
                # actually burning the loop — which is what a CPU
                # profile needs to show.
                profiler.note_task(spec)
                try:
                    result = await method(*args, **kwargs)
                finally:
                    RUNTIME_CTX.task_spec = None
                    RUNTIME_CTX.actor_id = None
                    profiler.clear_task()
            else:
                # Sync method on an async actor: run off-loop so it may
                # block (e.g. a controller's run() that get()s on workers).
                def _call(spec=spec):
                    RUNTIME_CTX.task_spec = spec
                    RUNTIME_CTX.actor_id = spec.actor_id
                    profiler.note_task(spec)
                    try:
                        return method(*args, **kwargs)
                    finally:
                        RUNTIME_CTX.task_spec = None
                        RUNTIME_CTX.actor_id = None
                        profiler.clear_task()
                result = await loop.run_in_executor(None, _call)
                if asyncio.iscoroutine(result):
                    result = await result
            if _is_small_result(result):
                return self._package_returns(spec, result)
            return await loop.run_in_executor(
                None, self._package_returns, spec, result)
        except Exception as e:  # noqa: BLE001
            return {"error": TaskError(spec.method_name,
                                       traceback.format_exc(), cause=e)}
        finally:
            if span_start is not None:
                from ..util.tracing import record_child_span
                record_child_span(
                    "task:" + (spec.name or spec.method_name
                               or spec.function.display_name()),
                    tuple(spec.trace_context), span_start, time.time(),
                    task_id=spec.task_id.hex())

    def _setup_actor(self, spec: TaskSpec):
        # adopt the creating job: background asyncio work this actor
        # spawns (outside any task context) must submit/log under it
        self._cw.job_id = spec.job_id
        self._is_asyncio = spec.is_asyncio
        if spec.is_asyncio:
            self._actor_async_sem = asyncio.Semaphore(
                max(1, spec.max_concurrency))
        elif spec.max_concurrency > 1:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=spec.max_concurrency,
                thread_name_prefix="rtpu-actor")
        for name, size in (spec.concurrency_groups or {}).items():
            self._actor_pools[name] = concurrent.futures.ThreadPoolExecutor(
                max_workers=size, thread_name_prefix=f"rtpu-cg-{name}")

# ---------------------------------------------------------------------------
# CoreWorker
# ---------------------------------------------------------------------------

class CoreWorker:
    def __init__(self, mode: str, session_name: str, gcs_address: Address,
                 raylet_address: Address, node_id: str, node_index: int,
                 job_id: Optional[JobID] = None,
                 worker_id: Optional[bytes] = None):
        self.mode = mode  # "driver" | "worker"
        self.session_name = session_name
        self.worker_id = worker_id or WorkerID.from_random().binary()
        self.node_id = node_id
        self.node_index = node_index
        self.raylet_address = tuple(raylet_address)
        self.server = RpcServer(f"{mode}-{self.worker_id.hex()[:8]}")
        self.clients = ClientPool()
        self.rpc_address: Optional[Address] = None
        self.gcs = GcsClient(gcs_address, local_server=self.server)
        self.memory_store = MemoryStore()
        self.plasma = PlasmaDir(session_name, node_index)
        self.task_events = TaskEventBuffer(self)
        from .runtime_env import RuntimeEnvManager
        self.runtime_env_manager = RuntimeEnvManager(
            os.path.join("/tmp", "rtpu", f"session_{session_name}",
                         "runtime_env"))
        # Owner shards: ownership state partitions across N io loops
        # keyed by hash(task_id/actor_id) % N (owner_shards.py). With
        # one shard (RTPU_OWNER_SHARDS=1, and every worker process)
        # shard 0 aliases the main loop/server/pool and the plain
        # TaskManager/ReferenceCounter above stay in place — the
        # exact-legacy A/B path.
        self.shards = ShardSet(resolve_shard_count(mode))
        if len(self.shards) > 1:
            self.reference_counter = ShardedReferenceCounter(
                self, len(self.shards))
            self.task_manager = ShardedTaskManager(self, len(self.shards))
        else:
            self.reference_counter = ReferenceCounter(self)
            self.task_manager = TaskManager(self)
        for shard in self.shards:
            shard.submitter = NormalTaskSubmitter(self, shard)
            shard.actor_submitter = ActorTaskSubmitter(self, shard)
        # Legacy aliases: shard 0's submitters (the only ones when n=1).
        self.submitter = self.shards.main.submitter
        self.actor_submitter = self.shards.main.actor_submitter
        self._actor_subscribed = False
        self._actor_sub_lock = threading.Lock()
        self._actor_sub_fut: Optional[concurrent.futures.Future] = None
        self.executor = TaskExecutor(self)
        self.function_manager = FunctionManager(self.gcs)
        self.job_id = job_id or JobID.from_int(0)
        self.current_lease_id: Optional[int] = None
        self._node_addr_cache: Dict[str, Address] = {}
        self._job_envs: Dict[JobID, "asyncio.Future"] = {}
        self._pending_frees: List[str] = []
        self._free_lock = threading.Lock()
        self._done_batches: Dict[Address, List] = {}
        # Native receive path (PR 11): resolved per CoreWorker so the
        # RTPU_NO_NATIVE_DECODE A/B can flip between init cycles in one
        # process (workers resolve from their inherited environment).
        self._no_native_decode = not native_decode.enabled()
        # Outbound borrow-decref folds: owner address -> packed id
        # bytes, flushed once per loop tick as one borrow_decref_fold
        # frame per owner instead of one borrow_decref RPC per object.
        self._decref_pending: Dict[Address, bytearray] = {}
        self._decref_lock = threading.Lock()
        self._decref_flush_scheduled = False
        # The loop serving this process's RpcServer (set at start()):
        # receive-path timers — push-record TTL sweeps, done-batch
        # flushes — schedule on THIS handle explicitly, never on the
        # ambient loop (>1 loop exists once owner shards are up, and
        # asyncio.get_event_loop() is deprecated under 3.12 anyway).
        self._serve_loop: Optional[asyncio.AbstractEventLoop] = None
        # normal-task pushes currently known to this worker (arrival ->
        # reply), served to owner-side push probes
        self._received_pushes: Set[TaskID] = set()
        # Completed push replies retained briefly: if the push's reply
        # frame is lost on a congested link, the owner's probe fetches
        # the cached reply instead of dropping the lease and
        # RE-EXECUTING the task (duplicate side effects). Reference
        # analog: task replies ride gRPC, which resends at the
        # transport level; this wire has no transport resend, so the
        # probe doubles as the ack/retry channel. Keyed by (task_id,
        # attempt): INTENTIONAL re-executions (error retries, lineage
        # reconstruction) bump attempt_number and must miss this cache.
        self._completed_push_replies: Dict[Tuple[TaskID, int],
                                           Dict[str, Any]] = {}
        self._completed_push_bytes = 0
        self._push_record_ttl: collections.deque = collections.deque()
        self._push_sweeper_on = False
        # 1/64 sampling counter for the per-shard submit histogram
        # (GIL-atomic int ops; racing submitters only skew the phase).
        self._submit_tick = 0
        # Called with the ObjectID whenever an owned object is freed
        # (device-resident object pins, experimental/device_objects.py).
        self.device_object_free_hooks: List = []
        self._shutdown = False

    # -- lifecycle -------------------------------------------------------

    def start(self):
        loop_thread = EventLoopThread.get()
        self._serve_loop = loop_thread.loop
        self.server.register_instance(self)
        # Flat task paths: raw frames bypass the kwargs pickler.
        self.server.register_raw("push_actor_tasks",
                                 self._handle_push_actor_tasks_raw)
        self.server.register_raw("push_task", self._handle_push_task_raw)
        # Native receive path: arm (or disarm — the A/B can flip per
        # init) the in-ring decoder, route its pre-decoded events, and
        # accept the two new raw wire forms. Handlers for BOTH forms
        # are registered unconditionally so mixed on/off peers
        # interoperate; the kill switch only gates what THIS process
        # sends and whether its rings decode.
        self._arm_native_decode()
        self.server.register_decoded("push_task",
                                     self._handle_push_task_decoded)
        self.server.register_decoded("push_actor_tasks",
                                     self._handle_push_actor_tasks_decoded)
        self.server.register_raw("borrow_decref_fold",
                                 self._handle_borrow_decref_fold_raw)
        self.rpc_address = loop_thread.run_sync(self.server.start())
        self.shards.start_main(loop_thread, self.server, self.clients,
                               self.rpc_address)
        self.shards.start_extra(f"{self.mode}-{self.worker_id.hex()[:8]}")
        for shard in self.shards:
            # Every shard's server folds ONLY its own done stream
            # (workers reply to the done_to the owning shard stamped on
            # the push) — reply routing never crosses shards, and ONE
            # decoder (the factory) serves main and extra shards alike.
            # Three registrations per shard, one stream: the legacy
            # pickled form, the raw packed form, and the C-validated
            # kind-5 event all land in the same per-shard fold.
            shard.server.register(
                "actor_tasks_done",
                self._make_done_stream_handler(shard.actor_submitter))
            raw_done = self._make_done_stream_raw_handler(
                shard.actor_submitter)
            shard.server.register_raw("actor_tasks_done", raw_done)
            shard.server.register_decoded("actor_tasks_done", raw_done)
        # GCS failover: when the client re-establishes itself on a new
        # incarnation, every shard replays its in-flight actor state
        # (pubsub published during the outage is gone for good).
        self.gcs.add_reconnect_hook(self._on_gcs_reconnected)
        profiler.maybe_autostart()
        from . import accel
        accel.install_import_hook()  # arm compile tracking at jax import

    def _on_gcs_reconnected(self):
        """GcsClient reconnect hook (runs on the main loop): fan the
        replay out to each owner shard's own loop."""
        for shard in self.shards:
            sub = shard.actor_submitter
            if shard.is_main:
                sub.replay_after_gcs_reconnect()
            else:
                shard.post_call(sub.replay_after_gcs_reconnect)

    def _arm_native_decode(self):
        """Apply this CoreWorker's native-decode setting to the C ring
        (process-wide flag + the ring-level decref-fold sink). Safe when
        the native library is unavailable: everything stays on the
        asyncio/legacy path and the raw handlers still understand the
        new wire forms."""
        try:
            from .._native.fastrpc import NativeIO
        except Exception:  # noqa: BLE001 — native optional by design
            logger.debug("native decode unavailable", exc_info=True)
            return
        on = NativeIO.apply_decode_config(not self._no_native_decode)
        NativeIO.set_fold_sink(self._apply_decref_fold if on else None)

    @staticmethod
    def _make_done_stream_handler(actor_submitter: "ActorTaskSubmitter"):
        """The actor_tasks_done decoder for the LEGACY pickled stream
        (bound per shard): a packed id array — one bytes blob per batch,
        replies aligned by index (the only sender is _flush_done, same
        build). Ids iterate as borrowed keys re-pointed at each 24-byte
        window of the ONE contiguous buffer — no bytes object per id
        even on the kill-switch arm, so the native-decode A/B measures
        the C-vs-Python delta, not allocator noise (on_done only looks
        the key up; the retained id is the spec's own task_id)."""
        async def handle_actor_tasks_done(ids: bytes, replies):
            for key, reply in zip(TaskID.iter_borrowed(ids), replies):
                actor_submitter.on_done(key, reply)
        return handle_actor_tasks_done

    @staticmethod
    def _make_done_stream_raw_handler(
            actor_submitter: "ActorTaskSubmitter"):
        """The actor_tasks_done decoder for the raw packed stream —
        serving both the asyncio raw frame and the C ring's validated
        kind-5 event (identical layout: u32 n | contiguous ids |
        batch-pickled replies)."""
        async def handle_actor_tasks_done_raw(payload):
            ids, replies = native_decode.unpack_done_stream(bytes(payload))
            for key, reply in zip(TaskID.iter_borrowed(ids), replies):
                actor_submitter.on_done(key, reply)
        return handle_actor_tasks_done_raw

    def shutdown(self):
        self._shutdown = True
        try:
            from .._native.fastrpc import NativeIO
            NativeIO.set_fold_sink(None)
        except Exception:  # noqa: BLE001 — native optional by design
            logger.debug("fold sink clear failed", exc_info=True)
        acc = 0
        for shard in self.shards:
            acc += shard.actor_submitter._wire_bytes_acc  # cross-shard ok: teardown, loops quiesced
            shard.actor_submitter._wire_bytes_acc = 0  # cross-shard ok: teardown, loops quiesced
        if acc:
            # Residual wire-bytes below the batching threshold would
            # otherwise never reach the counter (short-lived drivers
            # would report 0).
            from .runtime_metrics import runtime_metrics
            runtime_metrics().wire_task_bytes.inc(acc)
        for shard in self.shards:
            try:
                shard.run_sync(
                    shard.submitter.cancel_pending_requests(), timeout=5)
            except Exception:
                logger.debug("cancel_pending_requests failed during "
                             "shutdown", exc_info=True)
        try:
            EventLoopThread.get().run_sync(self.server.stop(), timeout=5)
        except Exception:
            logger.debug("rpc server stop failed during shutdown",
                         exc_info=True)
        # Extra owner shards: reply servers, cached clients, loops,
        # rings — joined here (the threads registry re-joins as a
        # backstop at node teardown).
        self.shards.stop()

    def current_job_id(self) -> JobID:
        """The job of the task being executed, else this process's job —
        nested submissions stay inside the driver's job without mutating
        shared worker state. A worker adopts the first job it executes
        for (reference: workers are pooled per job), so background
        asyncio tasks inside actors (serve reconcile loops) submit under
        the right job instead of the nil job."""
        spec = RUNTIME_CTX.task_spec
        return spec.job_id if spec is not None else self.job_id

    # -- plumbing --------------------------------------------------------

    def loop_call(self, coro):
        return EventLoopThread.get().call_soon(coro)

    def loop_post(self, coro):
        """Fire-and-forget on the io loop; wakeups batched across a burst."""
        EventLoopThread.get().post(coro)

    def run_sync(self, coro, timeout=None):
        return EventLoopThread.get().run_sync(coro, timeout)

    def fire_and_forget(self, address: Address, method: str,
                        _retries: int = 0, **kwargs):
        """Best-effort call on the main loop (shared semantics + the
        _retries idempotency caveat live in owner_shards.fire_and_forget)."""
        _fire_and_forget(self.clients, self.loop_post, address, method,
                         _retries=_retries, **kwargs)

    # -- batched borrow-decref folds (the refcount leg of the native
    # receive path) ------------------------------------------------------

    def queue_borrow_decref(self, owner: Address, object_id: ObjectID):
        """Release one borrowed ref toward its owner. Native path:
        append the raw id to the per-owner fold and flush ONE
        borrow_decref_fold frame per owner per loop tick (a completing
        dep list costs one frame, and the owner's C ring folds frames
        from many workers into one wakeup). Kill-switch path: the
        legacy one-RPC-per-object borrow_decref. Callable from any
        thread (ObjectRef finalizers release borrowed refs off-loop)."""
        if self._no_native_decode:
            self.fire_and_forget(owner, "borrow_decref",
                                 object_hex=object_id.hex())
            return
        owner = (owner[0], int(owner[1]))
        with self._decref_lock:
            buf = self._decref_pending.get(owner)
            if buf is None:
                buf = self._decref_pending[owner] = bytearray()
            buf += object_id.binary()
            if self._decref_flush_scheduled:
                return
            self._decref_flush_scheduled = True
        self.loop_post(self._flush_decref_folds())

    async def _flush_decref_folds(self):
        with self._decref_lock:
            pending, self._decref_pending = self._decref_pending, {}
            self._decref_flush_scheduled = False
        for owner, buf in pending.items():
            client = self.clients.get(owner)
            try:
                await client.oneway_raw("borrow_decref_fold", bytes(buf))
            except Exception:
                # Same delivery contract as the legacy per-object
                # oneway: best effort — a dead owner has no refs left
                # to count.
                logger.debug("borrow_decref_fold to %s dropped", owner,
                             exc_info=True)

    async def _handle_borrow_decref_fold_raw(self, payload):
        """The raw-frame twin of the kind-6 ring fold (asyncio
        transport / in-process fast path)."""
        self._apply_decref_fold(payload)

    def _apply_decref_fold(self, payload):
        """Apply one fold of borrower decrements: one pass, one lock
        acquisition per refcount stripe (also the NativeIO kind-6 fold
        sink, called from whichever loop drains the ring — the counter
        is thread-safe)."""
        ids = [ObjectID(b) for b in native_decode.iter_fold_ids(payload)]
        if ids:
            self.reference_counter.remove_borrowers_fold(ids)

    # -- cross-shard plumbing --------------------------------------------

    @property
    def _tmpl_sent(self):
        """Union of the per-shard flat-wire announce records. Read-only
        diagnostic (tests / the verify probe); the mutable state lives
        on each shard (`OwnerShard.tmpl_sent`), loop-confined."""
        out = set()
        for shard in self.shards:
            out |= shard.tmpl_sent  # cross-shard ok: racy diagnostic snapshot
        return out

    async def gcs_call(self, method: str, **kwargs):
        """GCS call awaitable from ANY owner-shard loop. The GcsClient's
        connection (and its pending-reply futures) are main-loop-affine,
        so a caller on an extra shard's loop hops through the main loop
        instead of touching the client's state cross-thread. On the main
        loop itself this is a zero-hop direct call (the shards=1 legacy
        path compiles down to exactly the old behavior)."""
        main_loop = self._serve_loop
        if main_loop is None or asyncio.get_running_loop() is main_loop:
            return await self.gcs.call(method, **kwargs)
        cfut = asyncio.run_coroutine_threadsafe(
            self.gcs.call(method, **kwargs), main_loop)
        return await asyncio.wrap_future(cfut)

    async def fetch_worker_postmortem(self, worker_id) -> Optional[dict]:
        """Brief poll for a dead worker's postmortem (log & forensics
        plane): the raylet's liveness sweep reports the death up to
        ~1s after the caller's push fails, so WorkerCrashedError
        construction waits a bounded window for the report rather than
        raising without the worker's last words. Returns None on
        timeout, GCS trouble, or under the kill switch."""
        if CONFIG.no_log_plane:
            return None
        whex = worker_id.hex() if isinstance(worker_id, bytes) \
            else str(worker_id)
        deadline = time.monotonic() + CONFIG.postmortem_fetch_timeout_s
        while True:
            # per-call timeout stays inside the overall budget: a slow
            # GCS must not stretch the documented bound on raising
            remaining = deadline - time.monotonic()
            try:
                pm = await self.gcs_call("get_worker_postmortem",
                                         worker_hex=whex,
                                         timeout=max(0.25, remaining))
            except Exception:
                logger.debug("postmortem fetch for %s failed", whex[:12],
                             exc_info=True)
                return None
            if pm is not None or time.monotonic() >= deadline:
                return pm
            await asyncio.sleep(0.25)

    async def ensure_actor_subscribed(self):
        """ONE GCS actor-pubsub subscription per process, establishable
        from any shard loop. The first caller subscribes (on the main
        loop — pubsub frames arrive at the main server) with a fan-out
        callback that routes each update to the owning shard's mailbox;
        concurrent callers from other shards await the same future."""
        if self._actor_subscribed:
            return
        with self._actor_sub_lock:
            fut = self._actor_sub_fut
            leader = fut is None
            if leader:
                fut = self._actor_sub_fut = concurrent.futures.Future()
        if not leader:
            await asyncio.wrap_future(fut)
            return
        try:
            main_loop = self._serve_loop
            coro = self.gcs.subscribe("ACTOR", self._on_actor_update_fanout)
            if main_loop is None or \
                    asyncio.get_running_loop() is main_loop:
                await coro
            else:
                await asyncio.wrap_future(
                    asyncio.run_coroutine_threadsafe(coro, main_loop))
            self._actor_subscribed = True
            fut.set_result(True)
        except BaseException as e:  # noqa: BLE001 — propagate after reset
            with self._actor_sub_lock:
                self._actor_sub_fut = None  # next caller retries
            fut.set_exception(e)
            # Exception was handed to the waiters; consuming it here too
            # keeps "no waiters" runs from logging it as unretrieved.
            fut.exception()
            raise

    async def _on_actor_update_fanout(self, message: Dict[str, Any]):
        """Pubsub fan-out (runs on the main loop): an actor's state
        updates apply on the shard that owns it — same hash routing as
        submission, so the update lands where the ActorClientState
        lives."""
        shard = self.shards.for_actor(message["actor_id"])
        if shard.is_main:
            await shard.actor_submitter._on_actor_update(message)
        else:
            shard.post(shard.actor_submitter._on_actor_update(message))

    def route_submit(self, spec: TaskSpec):
        """Submit/resubmit `spec` on the shard that owns its id (retries
        and reconstructions re-enter the original's loop-confined
        state: same id -> same shard)."""
        shard = self.shards.for_spec(spec)
        if spec.task_type == ACTOR_TASK:
            shard.actor_submitter.submit(spec)
        else:
            shard.submitter.submit(spec)

    async def ensure_job_env(self, job_id: JobID):
        """Adopt the driver's sys.path so its locally-defined functions
        deserialize here (reference: runtime-env path propagation).
        Concurrent callers await one in-flight fetch; failures are retried
        by the next task instead of being cached."""
        done = self._job_envs.get(job_id)
        if done is not None:
            if done.done():  # steady state: no await, no loop yield
                return
            await done
            return
        fut = asyncio.get_running_loop().create_future()
        self._job_envs[job_id] = fut
        try:
            raw = await self.gcs.call("kv_get", ns="job_meta",
                                      key=job_id.hex())
        except Exception:
            del self._job_envs[job_id]  # transient: let the next task retry
            fut.set_result(None)
            return
        if raw:
            import sys
            meta = serialization.loads(raw)
            paths = list(meta.get("sys_path", []))
            cwd = meta.get("cwd")
            if cwd:
                paths.append(cwd)  # the driver's '' (cwd) sys.path entry
            for path in reversed(paths):
                if path and path not in sys.path:
                    sys.path.insert(0, path)
        fut.set_result(None)

    def reclaim_idle_leases(self, exclude=None):
        """Cross-shard idle-lease recall (grant-time, not cleaner-tick):
        posts onto every other shard's loop, where the shard returns its
        genuinely idle leases to the raylet immediately so a starving
        peer's queued lease request can grant. Thread-safe: only the
        coroutine OBJECT is built here; every table touch happens on the
        owning shard's loop."""
        for shard in self.shards:
            if shard is exclude:
                continue
            shard.post(shard.submitter.reclaim_idle_now())

    async def node_address(self, node_id: str) -> Optional[Address]:
        addr = self._node_addr_cache.get(node_id)
        if addr is not None:
            return addr
        # gcs_call, not gcs.call: node_affinity lease requests await this
        # from owner-shard loops (the GcsClient is main-loop-affine).
        nodes = await self.gcs_call("get_all_nodes")
        for n in nodes:
            self._node_addr_cache[n["node_id"]] = tuple(n["address"])
        return self._node_addr_cache.get(node_id)

    # -- public object API ----------------------------------------------

    def put(self, value: Any, _owner_address: Optional[Address] = None
            ) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() of an ObjectRef is not allowed")
        oid = ObjectID.from_random()
        sobj = serialization.serialize(value)
        owner = _owner_address or self.rpc_address
        callsite = _capture_callsite()
        nbytes = sobj.total_bytes()
        if sobj.contained_refs:
            self.reference_counter.add_contained(
                [r.id() for r in sobj.contained_refs])
        if nbytes <= CONFIG.max_direct_call_object_size:
            # Small puts stay in-process; borrowers fetch via get_object rpc.
            self.reference_counter.add_owned(oid, in_plasma=False,
                                             size=nbytes, callsite=callsite)
            self.memory_store.put(oid, value)
        else:
            self.reference_counter.add_owned(oid, in_plasma=True,
                                             size=nbytes, callsite=callsite)
            self.put_serialized_to_plasma(oid, sobj, owner=owner)
        return ObjectRef(oid, owner)

    def put_serialized_to_plasma(self, oid: ObjectID,
                                 sobj: serialization.SerializedObject,
                                 owner: Optional[Address]):
        from .runtime_metrics import runtime_metrics
        runtime_metrics().store_put_bytes.inc(sobj.total_bytes())
        self.plasma.put_serialized(oid, sobj)
        raylet = self.clients.get(self.raylet_address)
        raylet.call_sync("seal_object", object_hex=oid.hex(),
                         size=sobj.total_bytes(), owner_address=owner,
                         retries=CONFIG.rpc_max_retries)

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None
            ) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            out.append(self._get_one(ref, remaining))
        return out

    def get_async(self, ref: ObjectRef) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _work():
            try:
                fut.set_result(self._get_one(ref, None))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
        from .threads import spawn_daemon
        spawn_daemon(_work, name="rtpu-get-async")
        return fut

    def _get_one(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        oid = ref.id()
        deadline = None if timeout is None else time.monotonic() + timeout
        poll = 0.0005
        while True:
            entry = self.memory_store.get_entry(oid)
            if entry is not None and not entry.in_plasma:
                if entry.is_exception:
                    err = entry.value
                    if isinstance(err, TaskError):
                        raise err.as_instanceof_cause()
                    raise err
                return resolve_entry(entry)
            value, ok = self.plasma.get(oid)
            if ok:
                return value
            # Remote / not-yet-ready paths.
            if entry is not None and entry.in_plasma:
                result = self._pull_via_raylet(oid)
                if result:
                    continue
                if self._maybe_reconstruct(oid):
                    continue
                raise ObjectLostError(oid)
            if self.task_manager.is_pending(oid.task_id()):
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(f"get() timed out waiting for {ref}")
                self.memory_store.wait_ready([oid], 1,
                                             min(remaining or 0.2, 0.2))
                continue
            if not self.reference_counter.is_owner(oid):
                # Borrowed ref: ask the owner, then fall back to plasma pull.
                fetched = self._fetch_from_owner(ref)
                if fetched is not _MISSING:
                    return fetched
                if self._pull_via_raylet(oid):
                    continue
            else:
                if self._pull_via_raylet(oid):
                    continue
                if self._maybe_reconstruct(oid):
                    continue
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(f"get() timed out waiting for {ref}")
            time.sleep(poll)
            poll = min(poll * 2, 0.05)

    def _pull_via_raylet(self, oid: ObjectID) -> bool:
        # Bounded: a pull for an object that exists nowhere must fail back
        # into the caller's retry/timeout loop, not park forever.
        raylet = self.clients.get(self.raylet_address)
        try:
            reply = raylet.call_sync("pull_object", object_hex=oid.hex(),
                                     retries=CONFIG.rpc_max_retries)
        except Exception:
            return False
        return bool(reply.get("ok"))

    def _fetch_from_owner(self, ref: ObjectRef):
        owner = ref.owner_address()
        if owner is None or tuple(owner) == self.rpc_address:
            return _MISSING
        client = self.clients.get(owner)
        try:
            reply = client.call_sync("get_object", object_hex=ref.hex(),
                                     timeout=30)
        except Exception:
            return _MISSING
        if reply.get("data") is not None:
            return serialization.deserialize(reply["data"])
        return _MISSING

    def _maybe_reconstruct(self, oid: ObjectID) -> bool:
        """Lineage reconstruction (reference: object_recovery_manager.cc):
        resubmit the creating task if we own it and lineage is retained."""
        if not oid.is_task_return():
            return False
        spec = self.task_manager.lineage_spec(oid.task_id())
        if spec is None:
            return False
        entry = self.memory_store.get_entry(oid)
        if entry is not None and not entry.in_plasma:
            # The reply landed while we were concluding "lost" (the
            # getter reads entry -> None, then on_completed puts the
            # value AND pops the pending row, then the getter's
            # is_pending check sees False and falls through to here).
            # Resubmitting would re-execute a COMPLETED task — a
            # doubled side effect. Report success; the caller's loop
            # re-reads the store and returns the value.
            return True
        logger.info("reconstructing %s by resubmitting task %s",
                    oid.hex()[:12], spec.name or spec.function.qualname)
        # Clear stale state and resubmit.
        self.memory_store.delete(spec.return_ids())
        spec.attempt_number += 1
        self.task_manager.add_pending(spec)
        dep_ids = [d for d, _ in spec.dependencies()]
        self.reference_counter.add_submitted(
            dep_ids + [c for a in spec.args for c in a.contained_ref_ids])
        self.route_submit(spec)
        # Wait for it to land.
        self.memory_store.wait_ready(spec.return_ids(), len(spec.return_ids()),
                                     timeout=CONFIG.rpc_call_timeout_s * 10)
        return True

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        """Reference: CoreWorker::Wait. Local readiness is event-driven
        (memory-store condition, notified on every completion); checks that
        need an RPC (borrowed/unknown objects, plasma pulls) are throttled
        to one sweep per 200 ms instead of every wakeup — a wait() over
        10k refs must not hammer the GCS."""
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        ready_set: Set[ObjectID] = set()
        remote_poll_at = 0.0
        while True:
            now = time.monotonic()
            poll_remote = now >= remote_poll_at
            if poll_remote:
                remote_poll_at = now + 0.2
            for ref in refs:
                oid = ref.id()
                if oid in ready_set:
                    continue
                ok = self._is_ready_local(oid)
                if ok is None and poll_remote:
                    ok = self._is_ready_remote(ref, fetch_local)
                if ok:
                    ready.append(ref)
                    ready_set.add(oid)
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            self.memory_store.wait_ready(
                [r.id() for r in refs if r.id() not in ready_set],
                1, timeout=0.05)
        not_ready = [r for r in refs if r.id() not in ready_set]
        return ready, not_ready

    def _is_ready_local(self, oid: ObjectID) -> Optional[bool]:
        """True/False from process-local state only; None = needs an RPC."""
        entry = self.memory_store.get_entry(oid)
        if entry is not None and not entry.in_plasma:
            return True
        if self.plasma.contains(oid):
            return True
        if entry is not None and entry.in_plasma:
            return None  # completed somewhere; pulling it is an RPC
        if self.task_manager.is_pending(oid.task_id()):
            return False
        return None  # unknown/borrowed: directory lookup is an RPC

    def _is_ready_remote(self, ref: ObjectRef, fetch_local: bool) -> bool:
        oid = ref.id()
        entry = self.memory_store.get_entry(oid)
        if entry is not None and entry.in_plasma:
            # Completed into plasma somewhere.
            if fetch_local:
                return self._pull_via_raylet(oid)
            return True
        # Unknown object (borrowed put, etc.): consult the directory.
        try:
            info = self.gcs.call_sync("get_object_locations",
                                      object_hex=oid.hex(), timeout=5)
        except Exception:
            return False
        known = bool(info.get("nodes") or info.get("spilled"))
        if known and fetch_local:
            return self._pull_via_raylet(oid)
        if not known and ref.owner_address() is not None:
            # Small owner-held object: ready iff the owner can serve it now.
            return self._fetch_from_owner(ref) is not _MISSING
        return known


    def free_objects(self, refs: List[ObjectRef]):
        for ref in refs:
            self._free_owned_object(ref.id())

    def _free_owned_object(self, object_id: ObjectID,
                           in_plasma: bool = True):
        for hook in self.device_object_free_hooks:
            try:
                hook(object_id)
            except Exception:
                logger.debug("free hook %r failed for %s", hook,
                             object_id.hex()[:12], exc_info=True)
        self.memory_store.delete([object_id])
        if not in_plasma:
            # Memory-store-only object: the GCS directory never heard of
            # it — skip the hex render + free RPC (the dominant free-path
            # cost on call floods, where every return is inline).
            return
        # Batch the directory-free notifications: a burst of ref releases
        # (e.g. a list of ObjectRefs going out of scope) becomes one GCS RPC.
        with self._free_lock:
            self._pending_frees.append(object_id.hex())
            if len(self._pending_frees) > 1:
                return  # drain already posted
        self.loop_post(self._drain_frees())

    async def _drain_frees(self):
        with self._free_lock:
            hexes, self._pending_frees = self._pending_frees, []
        if not hexes:
            return
        try:
            await self.gcs.call("free_objects", object_hexes=hexes,
                                timeout=10)
        except Exception:
            logger.debug("free_objects notify failed for %d objects "
                         "(directory entries persist until node death)",
                         len(hexes), exc_info=True)

    # -- task submission -------------------------------------------------

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        # Per-shard submit histogram, 1/64 sampled and only when >1
        # shard exists (shards=1 has no imbalance to see): this is the
        # hottest driver path and an unconditional observe() would tax
        # exactly the workloads the sharding speeds up.
        sample = self._submit_tick == 0 if len(self.shards) > 1 else False
        self._submit_tick = (self._submit_tick + 1) & 63
        t0 = time.monotonic() if sample else 0.0
        dep_ids = [oid for oid, _ in spec.dependencies()]
        contained = [c for a in spec.args for c in a.contained_ref_ids]
        self.task_manager.add_pending(spec, dep_ids, contained)
        if dep_ids or contained:
            self.reference_counter.add_submitted(dep_ids + contained)
        callsite = _capture_callsite()
        refs = [self.reference_counter.new_owned_ref(
                    oid, self.rpc_address, lineage_task=spec.task_id,
                    callsite=callsite)
                for oid in spec.return_ids()]
        shard = self.shards.for_spec(spec)
        if spec.task_type == ACTOR_TASK:
            shard.actor_submitter.submit(spec)
        else:
            shard.submitter.submit(spec)
        shard.submit_count += 1  # cross-shard ok: monotonic-ish counter, races only lose a tick
        if sample:
            from .runtime_metrics import runtime_metrics
            runtime_metrics().shard_submit.observe(
                time.monotonic() - t0, tags={"shard": shard.tag})
        return refs

    # -- rpc handlers ----------------------------------------------------

    async def _handle_push_task_raw(self, payload):
        """Flat lease push (rpc FLAG_RAW): header + optional template
        announce + delta, decoded straight into a freelist spec."""
        tid, lease_id, tmpl_data, delta = _unpack_push_task(payload)
        return await self.handle_push_task(
            lease_id=lease_id, tmpl=tid, frame=delta, tmpl_data=tmpl_data)

    async def _handle_push_task_decoded(self, payload):
        """Flat lease push the C ring already parsed (kind-3 event): the
        record carries the per-call fields pre-split, so the freelist
        spec fills from slices of ONE buffer — no incremental delta
        walk on the Python side."""
        _msg_id, lease_id, tid, tmpl_data, fields = \
            native_decode.parse_push_record(payload)
        if tmpl_data is not None:
            task_spec_codec.register_template(tid, tmpl_data)
        template = task_spec_codec.lookup_template(tid)
        if template is None:
            # C mirror said known but this registry evicted it: same
            # re-announce protocol as the raw path.
            return {"need_template": True}
        spec = task_spec_codec.spec_from_fields(template, *fields)
        return await self._execute_push(spec, lease_id, pooled=True)

    async def handle_push_task(self, spec: Optional[TaskSpec] = None,
                               lease_id: Optional[int] = None,
                               tmpl: Optional[bytes] = None,
                               frame: Optional[bytes] = None,
                               tmpl_data: Optional[bytes] = None):
        pooled = False
        if frame is not None:
            # Flat wire path: register any piggybacked template BEFORE
            # decoding (same-message announce — ordered by construction),
            # then decode the delta into a freelist spec.
            if tmpl_data is not None:
                task_spec_codec.register_template(tmpl, tmpl_data)
            template = task_spec_codec.lookup_template(tmpl)
            if template is None:
                return {"need_template": True}
            spec = task_spec_codec.decode_delta(frame, template)
            pooled = True
        return await self._execute_push(spec, lease_id, pooled)

    async def _execute_push(self, spec: TaskSpec,
                            lease_id: Optional[int], pooled: bool):
        """The shared execution tail of every push route (pickled spec,
        raw flat frame, C-decoded record): dedup, execute, cache the
        reply for probe recovery, release pooled specs."""
        if lease_id is not None:
            self.current_lease_id = lease_id
        # Duplicate push of the SAME attempt (owner re-sent after losing
        # our reply and re-leasing this same worker): serve the cached
        # reply, never re-execute. A bumped attempt_number (retry /
        # reconstruction) misses and runs for real.
        push_key = (spec.task_id, spec.attempt_number)
        cached = self._completed_push_replies.get(push_key)
        if cached is not None:
            from .runtime_metrics import runtime_metrics
            runtime_metrics().push_duplicates.inc()
            if pooled:
                task_spec_codec.release_spec(spec)
            return cached
        # known to this worker from arrival until WELL AFTER the reply —
        # the owner's push probe distinguishes a slow task from a lost
        # push. Discarding at reply time would race reply transmission
        # on a congested link: the probe would see "unknown" for a task
        # that just completed and kill a healthy worker.
        self._received_pushes.add(spec.task_id)
        try:
            reply = await self.executor.execute(spec)
        except BaseException:
            self._expire_push_record((spec.task_id, None))
            raise
        # Cache BEFORE the reply frame is written: a probe racing the
        # reply sees "done" rather than "unknown".
        self._completed_push_replies[push_key] = reply
        self._completed_push_bytes += _reply_nbytes(reply)
        if pooled:
            task_spec_codec.release_spec(spec)
        # Bound by entries AND bytes between TTL sweeps (large inline
        # returns would otherwise pin GBs for 120 s at high throughput).
        while self._completed_push_replies and (
                len(self._completed_push_replies) > 2048 or
                self._completed_push_bytes > 64 * 1024 * 1024):
            _k, _v = next(iter(self._completed_push_replies.items()))
            del self._completed_push_replies[_k]
            self._completed_push_bytes -= _reply_nbytes(_v)
        self._expire_push_record(push_key)
        return reply

    def _expire_push_record(self, push_key):
        """TTL the push record via ONE periodic sweeper instead of a
        TimerHandle per task (1M queued tasks would mean 2M live
        timers). Records expire 120-180 s after completion."""
        self._push_record_ttl.append((time.monotonic() + 120.0, push_key))
        if not self._push_sweeper_on:
            self._push_sweeper_on = True
            # Explicit handle: the record table is owned by the serve
            # loop, and with owner shards up there is more than one loop
            # in this process — the ambient-loop lookup is the one that
            # silently rescheduled sweeps onto the wrong loop.
            loop = self._serve_loop or asyncio.get_running_loop()
            loop.call_later(60.0, self._sweep_push_records)

    def _sweep_push_records(self):
        now = time.monotonic()
        q = self._push_record_ttl
        while q and q[0][0] <= now:
            _deadline, push_key = q.popleft()
            self._received_pushes.discard(push_key[0])
            reply = self._completed_push_replies.pop(push_key, None)
            if reply is not None:
                self._completed_push_bytes -= _reply_nbytes(reply)
        if q:
            loop = self._serve_loop or asyncio.get_running_loop()
            loop.call_later(60.0, self._sweep_push_records)
        else:
            self._push_sweeper_on = False

    async def handle_dump_stacks(self, path: str = "",
                                 quiet: bool = False) -> str:
        """Debug: render every thread's FULL stack (+ untruncated
        asyncio task stacks, with task attribution on executor threads)
        and RETURN the text so `cli stack` can aggregate it
        cluster-wide; also written to `path` or stderr for the
        postmortem-file callers (reference: the dashboard's on-demand
        py-spy capture)."""
        text = profiler.stack_dump_text(asyncio_tasks=asyncio.all_tasks())
        if path:
            with open(path, "w") as out:
                out.write(text)
        elif not quiet:
            sys.stderr.write(text)
        return text

    # -- continuous profiler control (reference: the reporter agent's
    # profiling RPCs routing py-spy; here the in-process sampler) ------

    async def handle_start_profiling(self, hz: Optional[float] = None,
                                     ring_size: Optional[int] = None):
        return profiler.start_profiling(hz=hz, ring_size=ring_size)

    async def handle_stop_profiling(self):
        return profiler.stop_profiling()

    async def handle_get_profile(self, clear: bool = True,
                                 stop: bool = False):
        report = profiler.get_profile(clear=clear, stop=stop)
        report["worker_id"] = self.worker_id.hex() \
            if isinstance(self.worker_id, bytes) else str(self.worker_id)
        report["node_id"] = self.node_id
        report["node_index"] = self.node_index
        report["component"] = self.mode
        return report

    async def handle_profiling_status(self):
        return dict(profiler.profiling_status(), component=self.mode,
                    node_id=self.node_id)

    async def handle_task_probe(self, task_hex: str, attempt: int = 0):
        """Owner-side push probe (see _push_with_probe): is this task
        known here — received/queued/running — and if it already
        finished, hand back the cached reply (lost-reply recovery)."""
        task_id = TaskID.from_hex(task_hex)
        reply = self._completed_push_replies.get((task_id, attempt))
        if reply is not None:
            return {"state": "done", "reply": reply}
        if task_id in self._received_pushes or \
                self.executor.is_running(task_id):
            return "running"
        return "unknown"

    async def _handle_push_actor_tasks_raw(self, payload):
        """Flat actor stream (rpc FLAG_RAW): announce templates, decode
        deltas into freelist specs, dispatch. A delta whose template is
        unknown (lost announce / registry pressure) still reports per
        task — the task id rides in the delta header — so the owner can
        re-announce and resend."""
        done_to, tmpls, frames = _unpack_actor_batch(payload)
        for tid, data in tmpls:
            task_spec_codec.register_template(tid, data)
        specs = []
        for tid, delta in frames:
            template = task_spec_codec.lookup_template(tid)
            if template is None:
                self._report_unknown_template(
                    done_to, task_spec_codec.peek_task_id(delta))
                continue
            specs.append(task_spec_codec.decode_delta(delta, template))
        await self.handle_push_actor_tasks(specs, done_to)

    async def _handle_push_actor_tasks_decoded(self, payload):
        """Flat actor stream the C ring already parsed (kind-4 event):
        per-record pre-split fields feed the freelist specs directly.
        The C `known` bit is advisory — a record whose template this
        registry lost anyway takes the same unknown-template report,
        using the task id the record carries."""
        done_to, tmpls, recs = \
            native_decode.parse_actor_batch_record(payload)
        for tid, data in tmpls:
            task_spec_codec.register_template(tid, data)
        specs = []
        for tid, _known, fields in recs:
            # This registry is authoritative; the C known-bit is only a
            # hint and is deliberately ignored here — a stale mirror
            # (evictions advance independently) must cost speed, never
            # spurious unknown-template errors for shapes we DO hold.
            template = task_spec_codec.lookup_template(tid)
            if template is None:
                self._report_unknown_template(done_to, fields[0])
                continue
            specs.append(
                task_spec_codec.spec_from_fields(template, *fields))
        await self.handle_push_actor_tasks(specs, done_to)

    def _report_unknown_template(self, done_to, task_id_bytes: bytes):
        """Queue an unknown-template system error onto the done batch
        for `done_to` (the owner re-announces and resends)."""
        q = self._done_batches.setdefault(done_to, [])
        q.append((bytes(task_id_bytes),
                  {"system_error": "unknown template"}))
        if len(q) == 1:
            asyncio.get_running_loop().call_soon(
                lambda d=done_to: asyncio.ensure_future(
                    self._flush_done(d)))

    async def handle_push_actor_tasks(self, specs: List[TaskSpec],
                                      done_to):
        """One-way actor task stream (reference: PushActorTask). Each spec
        executes under the actor's sequence ordering; completions flow
        back on the batched `actor_tasks_done` stream to `done_to`."""
        done_to = tuple(done_to)
        seen_jobs = set()
        for spec in specs:
            if spec.job_id not in seen_jobs:
                seen_jobs.add(spec.job_id)
                # once per job per batch (was per task inside execute())
                await self.ensure_job_env(spec.job_id)
            try:
                fut = self.executor.submit_actor_task(spec)
            except BaseException as e:  # noqa: BLE001 — must report
                self._report_actor_done(
                    spec, done_to,
                    {"system_error": f"executor failed: {e!r}"})
                continue
            fut.add_done_callback(
                lambda f, spec=spec: self._on_actor_task_future(
                    spec, done_to, f))

    def _on_actor_task_future(self, spec: TaskSpec, done_to: Address, fut):
        if fut.cancelled():
            return  # shutdown/kill: owner recovers via pubsub or sweep
        e = fut.exception()
        if e is not None:
            # Infrastructure failure (env setup, dispatch) — NOT an
            # application error: the owner requeues instead of failing.
            reply = {"system_error": f"executor failed: {e!r}"}
        else:
            reply = fut.result()
        self._report_actor_done(spec, done_to, reply)

    def _report_actor_done(self, spec: TaskSpec, done_to: Address, reply):
        q = self._done_batches.setdefault(done_to, [])
        # raw id bytes: a hex() here + from_hex() on the owner showed up
        # at ~3us/call on n:n floods
        q.append((spec.task_id.binary(), reply))
        if len(q) == 1:
            # Done-batch flush: scheduled on the serve loop that owns
            # _done_batches (this callback already runs there — the
            # explicit handle keeps it pinned once >1 loop exists).
            loop = self._serve_loop or asyncio.get_running_loop()
            loop.call_soon(
                lambda: asyncio.ensure_future(self._flush_done(done_to)))
        # codec-decoded specs go back to their freelist (no-op otherwise)
        task_spec_codec.release_spec(spec)

    async def _flush_done(self, done_to: Address):
        results = self._done_batches.pop(done_to, [])
        if not results:
            return
        client = self.clients.get(done_to)
        # Packed id array: one bytes blob for the whole batch instead of
        # a tuple-of-bytes per completion (cheaper to pickle and to walk).
        ids = b"".join(task_key for task_key, _reply in results)
        replies = [reply for _task_key, reply in results]
        try:
            if self._no_native_decode:
                await client.oneway("actor_tasks_done", ids=ids,
                                    replies=replies)
            else:
                # Raw packed stream: the owner's C ring validates the
                # id array in-ring (kind-5 event) and its Python side
                # pays one batch unpickle for the replies instead of a
                # kwargs pickle round trip per flush.
                await client.oneway_raw(
                    "actor_tasks_done",
                    native_decode.pack_done_stream(ids, replies))
        except Exception:
            # owner unreachable; actor-state pubsub recovers the rest
            logger.debug("actor_tasks_done to unreachable owner dropped",
                         exc_info=True)

    async def handle_actor_task_status(self, queries):
        """Straggler probe from an owner: for each (caller_hex, seq,
        task_hex), report done (with the cached reply), running, unknown
        (push never arrived — owner should resend), or lost (executed but
        the reply cache evicted it)."""
        ex = self.executor
        out = []
        for caller_hex, seq, task_hex in queries:
            caller = bytes.fromhex(caller_hex)
            cached = ex._reply_cache.get(caller, {}).get(seq)
            if cached is not None:
                out.append((task_hex, "done", cached))
            elif seq in ex._inflight.get(caller, {}) \
                    or seq in ex._seq_buffer.get(caller, {}):
                out.append((task_hex, "running", None))
            elif seq < ex._next_seq.get(caller, 0):
                out.append((task_hex, "lost", None))
            else:
                out.append((task_hex, "unknown", None))
        return out

    async def handle_get_shard_stats(self):
        """Owner-shard introspection: per-shard queue depth, loop lag,
        and submit counts (cli status and the dashboard node view render
        these rows — imbalance across shards is visible here)."""
        return {"pid": os.getpid(), "mode": self.mode,
                "worker_id": self.worker_id.hex()
                if isinstance(self.worker_id, bytes)
                else str(self.worker_id),
                "num_shards": len(self.shards),
                "shards": self.shards.stats()}

    async def handle_get_rpc_stats(self):
        """Transport-observatory introspection: this process's per-ring
        native stats, slow-RPC ring, and retry/transport-error totals
        (state.rpc_summary() fans this out cluster-wide)."""
        from . import rpc_metrics
        stats = rpc_metrics.local_stats()
        stats["worker_id"] = self.worker_id.hex() \
            if isinstance(self.worker_id, bytes) else str(self.worker_id)
        stats["mode"] = self.mode
        return stats

    async def handle_get_memory_report(self, limit: int = 10_000):
        """Owner-side memory introspection (reference: the per-worker
        reference-table dump behind `ray memory` / memory_summary()):
        every live reference with size, kind, creation callsite, and
        borrower counts."""
        objects, truncated = \
            self.reference_counter.memory_report_with_meta(limit=limit)
        total_refs = self.reference_counter.num_refs()
        from .runtime_metrics import runtime_metrics
        runtime_metrics().owned_refs.set(
            total_refs, tags={"pid": str(os.getpid())})
        wid = self.worker_id.hex() if isinstance(self.worker_id, bytes) \
            else str(self.worker_id)
        return {
            "worker_id": wid,
            "pid": os.getpid(),
            "mode": self.mode,
            "node_id": self.node_id,
            "node_index": self.node_index,
            "num_refs": total_refs,
            # Rows were dropped: consumers (the leak heuristic) must not
            # treat absence from `objects` as absence from the table.
            "truncated": truncated,
            "num_memory_store_objects": self.memory_store.size(),
            "num_pending_tasks": self.task_manager.num_pending(),
            "objects": objects,
        }

    async def handle_get_accel_report(self):
        """Accelerator-plane introspection: per-device HBM rows, XLA
        compile tracking, and step telemetry for THIS process (the
        device leg of the get_memory_report/get_profile family). Jax is
        only touched when this process already imported it — an
        observability sweep must never grab the TPU chip lock.
        Pressure rows found here are published to the GCS event log
        asynchronously (the handler runs on the serve loop, so the sync
        GCS bridge is off limits)."""
        from . import accel
        report = accel.accel_report()
        for pressed in report.get("pressure", ()):
            aio.spawn(self.gcs.call(
                "add_event", event_type="DEVICE_MEMORY_PRESSURE",
                message=(f"device {pressed['device']} "
                         f"({pressed['device_kind']}) HBM at "
                         f"{pressed['used_ratio']:.0%} of limit"),
                severity="WARNING",
                fields=dict(pressed, pid=os.getpid(),
                            node_id=self.node_id)))
        wid = self.worker_id.hex() if isinstance(self.worker_id, bytes) \
            else str(self.worker_id)
        report.update(worker_id=wid, mode=self.mode,
                      node_id=self.node_id, node_index=self.node_index)
        return report

    async def handle_get_object(self, object_hex: str):
        oid = ObjectID.from_hex(object_hex)
        entry = self.memory_store.get_entry(oid)
        if entry is None:
            return {"data": None}
        if entry.in_plasma:
            return {"data": None, "in_plasma": True}
        if entry.is_exception:
            return {"data": None, "error": True}
        if entry.raw is not None:
            raw = entry.raw
            if raw is not None:
                return {"data": raw}  # ref-free serialized form, as-is
        sobj = serialization.serialize(entry.value)
        self.reference_counter.pin_for_transit(sobj.contained_refs)
        return {"data": sobj.to_bytes()}

    async def handle_borrow_addref(self, object_hex: str):
        self.reference_counter.add_borrower(ObjectID.from_hex(object_hex))
        return True

    async def handle_borrow_decref(self, object_hex: str):
        self.reference_counter.remove_borrower(ObjectID.from_hex(object_hex))
        return True

    def cancel_task(self, ref: ObjectRef, force: bool = False,
                    recursive: bool = False) -> bool:
        """Owner-side cancel (reference: _private/worker.py cancel).

        Marks the task cancelled (its returns resolve to
        TaskCancelledError, late replies are dropped, no retries) and
        best-effort notifies the executing worker: queued tasks never
        start, running async actor tasks are asyncio-cancelled, and
        force=True kills the worker process outright. `recursive` is
        accepted for API parity; child tasks are not tracked yet.
        """
        task_id = ref.id().task_id()
        spec = self.task_manager.cancel(task_id)
        if spec is None:
            return False  # already finished (or not ours)
        if spec.task_type == ACTOR_TASK:
            # Queued specs stay in the stream (pushed as tombstones so the
            # actor's per-caller sequence numbering stays dense); a running
            # task is asyncio-cancelled on the actor.
            shard = self.shards.for_actor(spec.actor_id)
            st = shard.actor_submitter._actors.get(spec.actor_id)  # cross-shard ok: racy read, best-effort cancel notify
            if st is not None and st.address is not None:
                self.fire_and_forget(st.address, "cancel_task",
                                     task_hex=task_id.hex(), force=False)
        else:
            shard = self.shards.for_task(task_id)
            lease = shard.submitter._running.get(task_id)  # cross-shard ok: racy read, best-effort cancel notify
            if lease is not None:
                self.fire_and_forget(lease.worker_address, "cancel_task",
                                     task_hex=task_id.hex(), force=force)
            else:
                # Not pushed yet: drop any queued lease request so the
                # cancelled task stops competing for resources.
                self.fire_and_forget(self.raylet_address,
                                     "cancel_lease_by_task",
                                     task_hex=task_id.hex())
        return True

    async def handle_cancel_task(self, task_hex: str, force: bool = False):
        task_id = TaskID.from_hex(task_hex)
        if force:
            # Exit only if that task is actually still executing here — the
            # lease may have been returned and reused for an unrelated task
            # by the time this RPC lands.
            if self.executor.is_running(task_id):
                EventLoopThread.get().loop.call_later(0.05, os._exit, 1)
            else:
                self.executor.cancel(task_id)
            return True
        self.executor.cancel(task_id)
        return True

    async def handle_kill_actor(self, actor_id: ActorID):
        # Hard exit, like the reference's force-kill: no cleanup.
        EventLoopThread.get().loop.call_later(0.05, os._exit, 1)
        return True

    async def handle_ping(self, gcs_incarnation: Optional[int] = None):
        # The GCS's driver-liveness sweep piggybacks its incarnation on
        # the ping: a restart is detected within one sweep period even
        # when none of this process's own GCS calls ever failed (the
        # client then re-subscribes pubsub + replays in-flight state).
        if gcs_incarnation is not None:
            self.gcs.note_incarnation(gcs_incarnation)
        return "pong"

    async def handle_capture_profile(self, kind: str = "pystack",
                                     duration_s: float = 1.0):
        """On-demand profiling (reference: dashboard/modules/reporter/
        profile_manager.py:82 py-spy / memray; TPU equivalent = the jax
        profiler's xplane capture).

        kinds:
          pystack — sampled stacks of every thread, collapsed-stack text
                    (flamegraph input; the py-spy analog without py-spy)
          jax     — jax.profiler trace for `duration_s`; returns a zip of
                    the xplane/trace-event artifacts
        """
        duration_s = min(float(duration_s), 30.0)
        loop = asyncio.get_running_loop()
        if kind == "jax":
            def _jax_trace():
                import io as _io
                import zipfile
                import tempfile

                import jax
                with tempfile.TemporaryDirectory() as td:
                    with jax.profiler.trace(td):
                        time.sleep(duration_s)
                    buf = _io.BytesIO()
                    with zipfile.ZipFile(buf, "w",
                                         zipfile.ZIP_DEFLATED) as zf:
                        for root, _dirs, files in os.walk(td):
                            for f in files:
                                p = os.path.join(root, f)
                                zf.write(p, os.path.relpath(p, td))
                    return buf.getvalue()
            data = await loop.run_in_executor(None, _jax_trace)
            return {"kind": "jax", "format": "xplane-zip", "data": data}

        def _pystack():
            import collections
            import traceback
            counts: Dict[str, int] = collections.Counter()
            deadline = time.monotonic() + duration_s
            while time.monotonic() < deadline:
                for frame in list(sys._current_frames().values()):
                    stack = traceback.extract_stack(frame)
                    key = ";".join(f"{fr.name} ({os.path.basename(fr.filename)}"
                                   f":{fr.lineno})" for fr in stack)
                    counts[key] += 1
                time.sleep(0.01)
            text = "\n".join(f"{k} {v}" for k, v in
                             sorted(counts.items(), key=lambda kv: -kv[1]))
            return text.encode()
        data = await loop.run_in_executor(None, _pystack)
        return {"kind": "pystack", "format": "collapsed-stacks",
                "data": data}


_MISSING = object()
