"""Exception hierarchy for the runtime.

Mirrors the user-facing error surface of the reference
(python/ray/exceptions.py): task errors wrap the remote traceback, actor
errors carry death cause, object errors carry the object id.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RpcError(RayTpuError):
    """A control-plane RPC failed after retries."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get` exceeded its timeout."""


class TaskError(RayTpuError):
    """A remote task raised an exception.

    The remote traceback is captured as a string and re-raised on `get` with
    the original exception chained as ``cause`` when it could be pickled.
    """

    def __init__(self, function_name: str, traceback_str: str,
                 cause: Optional[BaseException] = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(self._format())

    def _format(self):
        return (
            f"remote task {self.function_name} failed\n"
            f"--- remote traceback ---\n{self.traceback_str}"
        )

    def __reduce__(self):
        cause = self.cause
        if cause is not None:
            try:
                import cloudpickle
                cloudpickle.dumps(cause)
            except Exception:
                cause = None  # unpicklable user exception: keep text only
        return (TaskError, (self.function_name, self.traceback_str, cause))

    def as_instanceof_cause(self):
        """Return an exception that is both a TaskError and isinstance of the
        user's exception type, so `except UserError:` works across the RPC
        boundary (reference: RayTaskError.as_instanceof_cause)."""
        if self.cause is None:
            return self
        cause_cls = type(self.cause)
        if cause_cls is TaskError or issubclass(TaskError, cause_cls):
            return self
        try:
            derived = type(
                "TaskError_" + cause_cls.__name__,
                (TaskError, cause_cls),
                {"__init__": lambda s: None},
            )
            err = derived()
            err.function_name = self.function_name
            err.traceback_str = self.traceback_str
            err.cause = self.cause
            err.args = (self._format(),)
            return err
        except TypeError:
            return self


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly.

    When the log & forensics plane is on, ``postmortem`` carries the
    raylet-assembled report for the dead worker (exit-code/signal
    taxonomy, its last captured log lines, recent task ids, a stack
    dump pointer) and the rendered report is appended to the message —
    the worker's last words arrive in the caller's exception."""

    def __init__(self, message: str = "",
                 postmortem: Optional[dict] = None):
        self.postmortem = postmortem
        if postmortem:
            from .logplane import render_postmortem
            message = f"{message}\n{render_postmortem(postmortem)}"
        super().__init__(message)

    def __reduce__(self):
        # rebuild from the FORMATTED message (postmortem already
        # rendered in) + keep the structured dict across the boundary
        return (_rebuild_worker_crashed,
                (self.args[0] if self.args else "", self.postmortem))


def _rebuild_worker_crashed(message: str, postmortem):
    err = WorkerCrashedError(message)
    err.postmortem = postmortem
    return err


class TaskCancelledError(RayTpuError):
    """The task was cancelled via `ray_tpu.cancel()` before it finished."""

    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"task {task_id} was cancelled")


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    def __init__(self, actor_id=None, cause: str = "actor died"):
        self.actor_id = actor_id
        super().__init__(f"actor {actor_id} is dead: {cause}")


class ActorUnavailableError(ActorError):
    """Actor is temporarily unreachable (restarting); call may be retried."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_id, message="object lost from the object store"):
        self.object_id = object_id
        super().__init__(f"{message}: {object_id}")


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class OutOfMemoryError(RayTpuError):
    """Task/worker was killed by the memory monitor."""


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


def format_current_exception() -> str:
    return traceback.format_exc()
