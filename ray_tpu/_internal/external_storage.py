"""Pluggable spill storage (reference: _private/external_storage.py —
ExternalStorage :72 filesystem, smart_open/S3 :398; here the cloud
driver is fsspec-based, so memory://, file://, s3://, gcs:// all ride
one implementation).

Selected by `CONFIG.object_spilling_uri`:
  ""                      -> node-local directory (fast rename path)
  "memory://rtpu-spill"   -> fsspec in-process memory fs (tests)
  "s3://bucket/prefix"    -> any fsspec-supported remote store
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)


class FsspecStorage:
    """Spill driver over an fsspec URL prefix."""

    def __init__(self, base_uri: str):
        import fsspec
        self.base_uri = base_uri.rstrip("/")
        self._fs, self._base_path = fsspec.core.url_to_fs(self.base_uri)
        try:
            self._fs.makedirs(self._base_path, exist_ok=True)
        except Exception:
            # Object stores (s3/memory) have no real directories.
            logger.debug("spill prefix makedirs skipped for %s",
                         self.base_uri, exc_info=True)

    def _path(self, key: str) -> str:
        return f"{self._base_path}/{key}"

    def uri_for(self, key: str) -> str:
        return f"{self.base_uri}/{key}"

    def put(self, key: str, data: bytes) -> str:
        with self._fs.open(self._path(key), "wb") as f:
            f.write(data)
        return self.uri_for(key)

    def get(self, uri: str) -> Optional[bytes]:
        import fsspec
        try:
            with fsspec.open(uri, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, uri: str):
        import fsspec
        fs, path = fsspec.core.url_to_fs(uri)
        try:
            fs.rm(path)
        except Exception:
            logger.debug("spilled-object delete failed for %s (orphaned "
                         "spill file)", uri, exc_info=True)


def storage_from_config() -> Optional[FsspecStorage]:
    from .config import CONFIG
    uri = getattr(CONFIG, "object_spilling_uri", "") or \
        os.environ.get("RTPU_OBJECT_SPILLING_URI", "")
    if not uri:
        return None
    try:
        return FsspecStorage(uri)
    except Exception:
        logger.exception("fsspec spill storage %r unavailable; "
                         "falling back to local-disk spilling", uri)
        return None
