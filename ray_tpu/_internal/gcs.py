"""Global Control Service (GCS).

Equivalent of the reference's GCS server (src/ray/gcs/: gcs_server.h,
gcs_node_manager, gcs_actor_manager, gcs_placement_group_manager/scheduler,
gcs_resource_manager, gcs_health_check_manager, gcs_kv_manager,
gcs_job_manager, pubsub_handler). One per cluster, owns all cluster metadata:

- node membership + active health checking of raylets
- the actor directory and actor lifecycle (schedule / restart / kill)
- placement groups with two-phase prepare/commit across raylets
- cluster resource view (built from raylet heartbeats; heartbeat replies
  carry the aggregated view back so every raylet can make spillback
  decisions — the role of the reference's RaySyncer gossip)
- internal KV (function registry, named actors, train rendezvous, etc.)
- cluster-wide pubsub (push-based; the reference uses long-poll)
- the object directory for shared-memory objects (location set per object)
- job table and task-event collection (state API / timeline backend)

Storage is in-memory tables with durable persistence underneath
(reference: the Redis-backed GCS fault-tolerance mode): every mutation
appends a typed record to a write-ahead log and a compactor folds the
log into a snapshot (`gcs_store.py`); recovery = snapshot + WAL-tail
replay. `RTPU_GCS_PERSIST=legacy|wal|off` selects the old whole-snapshot
path, the WAL path, or nothing. Each (re)start stamps a monotonic
**incarnation** id: clients carry the last incarnation they saw, so a
restarted GCS is detected (they re-register and replay in-flight state)
and a zombie pre-restart GCS rejects writes from clients that have
already seen its successor.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from . import aio
from .backoff import Backoff
from .config import CONFIG
from .errors import ActorDiedError, PlacementGroupError
from . import gcs_store
from .ids import ActorID, JobID, NodeID, PlacementGroupID
from .resources import NodeResources, ResourceSet
from .rpc import Address, ClientPool, RpcServer, get_loop
from .scheduling_policy import NodeView, pick_hybrid, pick_node_affinity, \
    pick_node_label, pick_spread, place_bundles
from . import serialization
from .task_spec import TaskSpec

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Tables
# ---------------------------------------------------------------------------

@dataclass
class NodeRecord:
    node_id: str
    address: Address            # raylet rpc address
    resources_total: Dict[str, float]
    labels: Dict[str, str]
    state: str = "ALIVE"        # ALIVE | DEAD
    node_index: int = 0
    session_name: str = ""
    last_heartbeat: float = 0.0
    missed_health_checks: int = 0
    is_head: bool = False


@dataclass
class ActorRecord:
    actor_id: ActorID
    spec: TaskSpec
    name: str = ""
    namespace: str = ""
    state: str = "PENDING"      # PENDING|ALIVE|RESTARTING|DEAD
    address: Optional[Address] = None     # worker rpc address
    node_id: Optional[str] = None
    worker_id: Optional[bytes] = None
    num_restarts: int = 0
    max_restarts: int = 0
    death_cause: str = ""
    is_detached: bool = False
    owner_address: Optional[Address] = None
    placement_group_id: Optional[PlacementGroupID] = None
    # Bumped on every (re)schedule decision; a stale _schedule_actor loop
    # observing a different epoch aborts (prevents two live instances).
    sched_epoch: int = 0


@dataclass
class PlacementGroupRecord:
    pg_id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str
    name: str = ""
    state: str = "PENDING"      # PENDING|CREATED|REMOVED|RESCHEDULING
    bundle_nodes: List[Optional[str]] = field(default_factory=list)
    creator_job: Optional[JobID] = None
    is_detached: bool = False


@dataclass
class JobRecord:
    job_id: JobID
    driver_address: Optional[Address]
    namespace: str = ""
    state: str = "RUNNING"
    start_time: float = 0.0
    end_time: float = 0.0
    metadata: Dict[str, str] = field(default_factory=dict)
    missed_pings: int = 0
    # Driver-supplied idempotency token: an add_job retry whose original
    # reply was lost (GCS restart mid-call) coalesces onto the existing
    # record instead of double-creating the job.
    token: str = ""


class GcsServer:
    def __init__(self, session_name: str, persist_path: Optional[str] = None):
        self.session_name = session_name
        self.persist_path = persist_path
        self.server = RpcServer("gcs")
        self.clients = ClientPool()
        self.address: Optional[Address] = None

        self.nodes: Dict[str, NodeRecord] = {}
        self.actors: Dict[ActorID, ActorRecord] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.pgs: Dict[PlacementGroupID, PlacementGroupRecord] = {}
        self.jobs: Dict[JobID, JobRecord] = {}
        self.kv: Dict[str, Dict[str, bytes]] = {}
        # object directory: obj hex -> (owner addr, set of node ids, size)
        self.object_dir: Dict[str, Tuple[Optional[Address], Set[str], int]] = {}
        self.spilled: Dict[str, str] = {}   # obj hex -> spilled path
        # per-node unmet lease demand, from heartbeats (autoscaler input)
        self._pending_demand: Dict[str, List[Dict[str, float]]] = {}
        # per-node oldest-pending-lease age per shape (autoscaler
        # state-manager input; reference: gcs_autoscaler_state_manager)
        self._queue_ages: Dict[str, Dict[str, float]] = {}
        # When WE last flipped a node's view drain flag (drain_node set
        # or cancel): heartbeat adoption of the raylet's own flag is
        # suppressed for a short grace after, so a pre-flip heartbeat
        # in flight can neither clear a just-raised fence nor re-raise
        # a just-canceled one. Past the grace the raylet's heartbeat is
        # authoritative BOTH ways (it survives a GCS failover; the
        # recovered view starts clean).
        self._drain_view_ts: Dict[str, float] = {}
        # pubsub: channel -> {subscriber addr}
        self.subscribers: Dict[str, Set[Address]] = {}
        # deque(maxlen): overflow drops the oldest entries in O(1) per
        # append (the old list-based ring shifted 100k entries with
        # del list[:n] on every overflow batch)
        self.task_events: collections.deque = collections.deque(
            maxlen=100_000)
        # Persistent structured cluster event log (reference:
        # src/ray/gcs/gcs_server/gcs_ray_event_converter + the event
        # export API): bounded, snapshot-persisted, queryable.
        self.events: collections.deque = collections.deque(
            maxlen=CONFIG.event_log_max_entries)
        self.actor_sched_lock: Optional[asyncio.Lock] = None

        self._resource_views: Dict[str, NodeView] = {}
        # Cluster-view delta state (reference: ray_syncer versioning).
        # The epoch token distinguishes GCS incarnations: a raylet's
        # known_ver from before a GCS restart must not be mistaken for a
        # valid baseline in the new numbering.
        self._view_version = 0
        self._view_epoch = int.from_bytes(os.urandom(8), "big")
        self._view_removals: List[Tuple[int, str]] = []
        self._removals_trimmed_ver = 0
        self._job_counter = 0
        self._spread_clock = 0
        self._next_node_index = 1
        self._health_task = None
        self._started = False
        # finished/dead jobs, hex -> monotonic finish time: raylets learn
        # of them via heartbeat replies and reap the job's worker leases
        # (reference: node_manager HandleJobFinished kills job workers)
        self._finished_jobs: Dict[str, float] = {}
        self._last_driver_sweep = 0.0
        # Worker postmortems (log & forensics plane): worker hex -> the
        # raylet-assembled report (exit taxonomy, last captured lines,
        # stack dump pointer). Bounded FIFO — crashing callers fetch by
        # the worker_id their dead lease named, shortly after death.
        self.worker_postmortems: "collections.OrderedDict[str, Dict]" = \
            collections.OrderedDict()
        # -- durability & incarnation --------------------------------------
        # Monotonic across restarts (recovered + 1): clients stamp the
        # incarnation they last saw so restarts are detectable and a
        # zombie pre-restart GCS can't accept writes from clients that
        # already follow its successor.
        self.incarnation = 1
        self._failovers = 0
        mode = CONFIG.gcs_persist if persist_path else "off"
        if mode not in ("wal", "legacy", "off"):
            logger.warning("unknown gcs_persist mode %r; using 'wal'", mode)
            mode = "wal"
        self._persist_mode = mode
        self._store = gcs_store.DurableStore(persist_path) \
            if mode == "wal" else None
        self._persist_fail_streak = 0
        self._last_persist_fail_event = 0.0
        self._wal_sync_scheduled = False
        self._compacting = False
        self._had_prior_state = False
        # Registration-event dedupe: (event_type, entity) pairs already
        # in the event log — a reconnect replaying a registration must
        # not double-fire JOB_STARTED/ACTOR_*/NODE_ALIVE rows. Seeded
        # from the recovered log so rows survive across incarnations.
        # Dict-as-ordered-set: overflow evicts the OLDEST entries (a
        # wholesale clear would re-enable double-fires for every live
        # entity at the next reconnect storm).
        self._event_dedupe: Dict[Tuple[str, str], None] = {}
        # Per-row sequence stamp: makes event WAL records idempotent on
        # replay (a crash between compact()'s snapshot rename and the
        # WAL truncation replays rows the snapshot already holds).
        self._event_seq = 0
        # SLO alert table (flight deck): bounded rows fired by the
        # alert engine (_internal/alerts.py) — in-memory like the rest
        # of the live observability plane; every fire also lands an
        # SLO_ALERT row in the persisted event log above.
        self.alerts: collections.deque = collections.deque(
            maxlen=CONFIG.alert_log_max_entries)
        self._alert_seq = 0
        # add_job idempotency-token index (token -> job id): O(1) dedupe
        # of retried registrations; rebuilt from job records at recovery.
        self._job_tokens: Dict[str, JobID] = {}

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Address:
        self.actor_sched_lock = asyncio.Lock()
        self.server.register_instance(self)
        self.address = await self.server.start(host, port)
        self._recover()
        if self._had_prior_state:
            self._failovers += 1
            from .runtime_metrics import runtime_metrics
            runtime_metrics().gcs_failovers.inc()
            self.add_event(
                "GCS_RESTARTED",
                f"gcs recovered ({self._persist_mode}) as incarnation "
                f"{self.incarnation}: {len(self.nodes)} nodes, "
                f"{len(self.actors)} actors, {len(self.jobs)} jobs",
                severity="WARNING", incarnation=self.incarnation,
                persist_mode=self._persist_mode)
            # Replay in-flight control work the old incarnation was
            # driving: actors mid-(re)schedule resume their loops, PGs
            # mid-placement resume theirs. ALIVE actors keep their
            # addresses — their workers live in raylets that survived.
            for record in self.actors.values():
                if record.state in ("PENDING", "RESTARTING"):
                    record.sched_epoch += 1
                    aio.spawn(self._schedule_actor(record),
                              what="schedule_actor")
            for pg in self.pgs.values():
                if pg.state in ("PENDING", "RESCHEDULING"):
                    aio.spawn(self._schedule_pg(pg), what="schedule_pg")
        if self._persist_mode == "wal":
            self._mutate("meta", "incarnation", self.incarnation)
            # Clean base for the new incarnation: fold the recovered WAL
            # tail into the snapshot so replay work never compounds.
            self._compact()
        elif self._persist_mode == "legacy":
            self._persist()
        self._health_task = asyncio.ensure_future(self._health_check_loop())
        self._started = True
        from . import profiler
        profiler.maybe_autostart()
        return self.address

    async def stop(self):
        if self._health_task:
            self._health_task.cancel()
        if self._store is not None:
            self._store.close()
        await self.server.stop()

    # ------------------------------------------------------------------
    # persistence (reference: redis store client; here WAL + snapshot —
    # gcs_store.py — with the legacy whole-snapshot path as the A/B arm)
    # ------------------------------------------------------------------

    def _snapshot_state(self) -> Dict[str, Any]:
        return {
            "nodes": self.nodes, "actors": self.actors,
            "named_actors": self.named_actors, "pgs": self.pgs,
            "jobs": self.jobs, "kv": self.kv,
            "job_counter": self._job_counter,
            "events": list(self.events),
            "incarnation": self.incarnation,
            "failovers": self._failovers,
        }

    def _persist(self):
        """Legacy mode: rewrite the whole snapshot (the pre-WAL behavior,
        kept as the `RTPU_GCS_PERSIST=legacy` A/B arm)."""
        if not self.persist_path or self._persist_mode != "legacy":
            return
        try:
            gcs_store.write_snapshot(
                self.persist_path,
                serialization.dumps(self._snapshot_state()))
        except Exception:
            logger.exception("gcs persist failed")
            self._note_persist_failure()
        else:
            self._note_persist_ok()

    def _mutate(self, kind: str, key: Any, value: Any,
                legacy_persist: bool = True):
        """Record one durable mutation. WAL mode appends a typed record
        (O(record), fsync group-committed per loop tick); legacy mode
        falls back to the full-snapshot rewrite for the call sites that
        persisted before (`legacy_persist=False` marks the new
        fine-grained sites — per-event and per-KV rows — that the old
        path only captured incidentally)."""
        if self._persist_mode == "off":
            return
        if self._persist_mode == "legacy":
            if legacy_persist:
                self._persist()
            return
        try:
            nbytes = self._store.append(kind, key, value)
        except Exception:
            logger.exception("gcs wal append failed")
            self._note_persist_failure()
            return
        self._note_persist_ok()
        from .runtime_metrics import runtime_metrics
        runtime_metrics().gcs_wal_bytes.inc(nbytes)
        if CONFIG.gcs_wal_fsync and not self._wal_sync_scheduled:
            # Group commit: one fsync per event-loop tick batch.
            self._wal_sync_scheduled = True
            try:
                asyncio.get_running_loop().call_soon(self._wal_sync)
            except RuntimeError:
                self._wal_sync()  # off-loop caller (unit tests)
        # _compacting guards REENTRY through the failure path, not
        # concurrency: a failing _compact emits GCS_PERSIST_FAILING via
        # add_event -> _mutate, which would otherwise re-enter _compact
        # (the log is still over threshold) and recurse.
        if self._store.wal.size > CONFIG.gcs_wal_compact_bytes \
                and not self._compacting:
            self._compact()

    def _wal_sync(self):
        self._wal_sync_scheduled = False
        try:
            self._store.wal.sync()
        except Exception:
            logger.exception("gcs wal fsync failed")
            self._note_persist_failure()

    def _compact(self):
        """Fold the WAL into the snapshot. Synchronous on the event loop
        (no awaits between building the state blob and cutting the log,
        so no record can land in the truncated window)."""
        if self._store is None:
            return
        self._compacting = True
        try:
            self._store.compact(
                serialization.dumps(self._snapshot_state()))
        except Exception:
            logger.exception("gcs wal compaction failed")
            self._note_persist_failure()
        else:
            self._note_persist_ok()
        finally:
            self._compacting = False

    def _note_persist_failure(self):
        """Make durability loss VISIBLE: count it, and after N
        consecutive failures emit a rate-limited event — a GCS whose
        disk is full must not degrade to an eternal logger.exception."""
        self._persist_fail_streak += 1
        from .runtime_metrics import runtime_metrics
        runtime_metrics().gcs_persist_failures.inc()
        now = time.monotonic()
        if self._persist_fail_streak >= \
                CONFIG.gcs_persist_failure_event_threshold \
                and now - self._last_persist_fail_event > 60.0:
            self._last_persist_fail_event = now
            self.add_event(
                "GCS_PERSIST_FAILING",
                f"{self._persist_fail_streak} consecutive GCS persist "
                f"failures ({self._persist_mode} mode) — cluster state "
                "is NOT being made durable",
                severity="ERROR", failures=self._persist_fail_streak,
                persist_mode=self._persist_mode)

    def _note_persist_ok(self):
        if self._persist_fail_streak:
            logger.warning("gcs persistence recovered after %d failures",
                           self._persist_fail_streak)
        self._persist_fail_streak = 0

    def _recover(self):
        if not self.persist_path or self._persist_mode == "off":
            return
        if self._persist_mode == "legacy":
            try:
                snap = gcs_store.load_snapshot(self.persist_path)
            except Exception:
                logger.exception("gcs restore failed")
                return
            records: List[Tuple[str, Any, Any]] = []
        else:
            try:
                snap, records = self._store.recover()
            except Exception:
                logger.exception("gcs recovery failed; starting empty")
                return
        if snap is None and not records:
            return
        self._had_prior_state = True
        if snap is not None:
            try:
                self.nodes = snap["nodes"]
                self.actors = snap["actors"]
                self.named_actors = snap["named_actors"]
                self.pgs = snap["pgs"]
                self.jobs = snap["jobs"]
                self.kv = snap["kv"]
                self._job_counter = snap["job_counter"]
                self.events = collections.deque(
                    snap.get("events", ()),
                    maxlen=CONFIG.event_log_max_entries)
                self._event_seq = max(
                    (e.get("seq", 0) for e in self.events), default=0)
                self.incarnation = snap.get("incarnation", 0)
                self._failovers = snap.get("failovers", 0)
            except Exception:
                logger.exception("gcs snapshot malformed; replaying WAL "
                                 "over empty tables")
        for kind, key, value in records:
            try:
                self._apply_record(kind, key, value)
            except Exception:
                logger.exception("gcs wal record (%s) unapplicable; "
                                 "skipped", kind)
        self.incarnation += 1
        # Registration rows already logged must not re-fire after the
        # re-registration storm that follows a restart.
        for ev in self.events:
            entity = ev.get("job_id") or ev.get("actor_id") \
                or ev.get("node_id")
            if entity:
                self._event_dedupe[(ev["type"], entity)] = None
        # Index allocation resumes past the recovered nodes — a fresh
        # joiner must not collide with a live node's index (metric tags
        # and state-API rows key on it).
        self._next_node_index = max(
            (r.node_index for r in self.nodes.values()), default=0) + 1
        # Rebuild the add_job token index from the recovered job table.
        self._job_tokens = {
            getattr(r, "token", ""): jid
            for jid, r in self.jobs.items() if getattr(r, "token", "")}
        # Nodes must heartbeat (or re-register) to prove liveness; mark
        # everything fresh until they do.
        for rec in self.nodes.values():
            rec.missed_health_checks = 0
            rec.last_heartbeat = time.monotonic()
        logger.warning(
            "gcs recovered as incarnation %d: %d nodes, %d actors, "
            "%d jobs, %d kv namespaces (%d wal records replayed)",
            self.incarnation, len(self.nodes), len(self.actors),
            len(self.jobs), len(self.kv), len(records))

    def _apply_record(self, kind: str, key: Any, value: Any):
        """Fold one WAL record into the tables (replay)."""
        if kind == "node":
            if value is None:
                self.nodes.pop(key, None)
            else:
                self.nodes[key] = value
        elif kind == "actor":
            if value is None:
                self.actors.pop(key, None)
            else:
                self.actors[key] = value
        elif kind == "named":
            if value is None:
                self.named_actors.pop(key, None)
            else:
                self.named_actors[key] = value
        elif kind == "job":
            self.jobs[key] = value
        elif kind == "pg":
            if value is None:
                self.pgs.pop(key, None)
            else:
                self.pgs[key] = value
        elif kind == "kv":
            ns, k = key
            if value is None:
                self.kv.get(ns, {}).pop(k, None)
            else:
                self.kv.setdefault(ns, {})[k] = value
        elif kind == "counter":
            self._job_counter = max(self._job_counter, value)
        elif kind == "event":
            # Idempotent replay: rows the snapshot already holds (a
            # crash between compact()'s rename and the WAL truncation
            # leaves them in both) are skipped by sequence stamp.
            seq = value.get("seq")
            if seq is not None and seq <= self._event_seq:
                return
            self.events.append(value)
            if seq is not None:
                self._event_seq = seq
        elif kind == "meta":
            if key == "incarnation":
                self.incarnation = max(self.incarnation, value)
        else:
            logger.warning("unknown gcs wal record kind %r", kind)

    # ------------------------------------------------------------------
    # pubsub
    # ------------------------------------------------------------------

    async def handle_subscribe(self, channel: str, address: Address):
        self.subscribers.setdefault(channel, set()).add(tuple(address))
        return True

    async def handle_unsubscribe(self, channel: str, address: Address):
        self.subscribers.get(channel, set()).discard(tuple(address))
        return True

    async def handle_publish(self, channel: str, message: Dict[str, Any]):
        """External publisher entry (raylets publishing worker logs etc.;
        reference: GcsPublisher)."""
        self.publish(channel, message)
        return True

    def publish(self, channel: str, message: Dict[str, Any]):
        subs = list(self.subscribers.get(channel, ()))
        for addr in subs:
            client = self.clients.get(addr)
            fut = asyncio.ensure_future(client.call(
                "pubsub_message", channel=channel, message=message,
                timeout=CONFIG.pubsub_push_timeout_s))
            fut.add_done_callback(
                lambda f, a=addr, c=channel: self._on_publish_done(f, a, c))

    def _on_publish_done(self, fut, addr, channel):
        exc = fut.exception() if not fut.cancelled() else None
        if exc is not None:
            # Dead subscriber: drop it.
            self.subscribers.get(channel, set()).discard(addr)

    # ------------------------------------------------------------------
    # KV
    # ------------------------------------------------------------------

    async def handle_kv_put(self, ns: str, key: str, value: bytes,
                            overwrite: bool = True):
        table = self.kv.setdefault(ns, {})
        if not overwrite and key in table:
            return False
        table[key] = value
        # Fine-grained durability the legacy path never had per-put (KV
        # only rode along with the next whole-state persist).
        self._mutate("kv", (ns, key), value, legacy_persist=False)
        return True

    async def handle_kv_get(self, ns: str, key: str):
        return self.kv.get(ns, {}).get(key)

    async def handle_kv_multi_get(self, ns: str, keys: List[str]):
        table = self.kv.get(ns, {})
        return {k: table[k] for k in keys if k in table}

    async def handle_kv_del(self, ns: str, key: str):
        existed = self.kv.get(ns, {}).pop(key, None) is not None
        if existed:
            self._mutate("kv", (ns, key), None, legacy_persist=False)
        return existed

    async def handle_kv_keys(self, ns: str, prefix: str = ""):
        return [k for k in self.kv.get(ns, {}) if k.startswith(prefix)]

    async def handle_kv_exists(self, ns: str, key: str):
        return key in self.kv.get(ns, {})

    # ------------------------------------------------------------------
    # nodes / resources / health
    # ------------------------------------------------------------------

    def _check_incarnation(self, caller_incarnation: Optional[int]) -> bool:
        """Zombie-GCS guard: a caller stamping a NEWER incarnation than
        ours has already registered with our successor — we are a stale
        process that must not accept its writes. Returns False when the
        call must be rejected."""
        return not (caller_incarnation is not None
                    and caller_incarnation > self.incarnation)

    async def handle_register_node(self, node_id: str, address: Address,
                                   resources: Dict[str, float],
                                   labels: Dict[str, str],
                                   is_head: bool = False,
                                   worker_ids: Optional[List[str]] = None,
                                   gcs_incarnation: Optional[int] = None):
        if not self._check_incarnation(gcs_incarnation):
            return {"stale_gcs": True, "incarnation": self.incarnation}
        rec = self.nodes.get(node_id)
        if rec is not None and rec.state == "DEAD":
            # Fencing: a node we declared DEAD had its actors failed
            # over — letting it back in would resurrect their stale
            # worker instances alongside the replacements (doubled
            # actors). Same contract as the heartbeat path: a declared-
            # dead raylet exits; its host rejoins as a FRESH node id.
            return {"dead": True, "incarnation": self.incarnation}
        if rec is not None:
            # Reconnect-and-replay: the raylet re-announces itself after
            # a GCS restart (or after its own network blip). Keep its
            # identity (node_index), refresh address/resources, and
            # reconcile the announced worker inventory against the actor
            # table — actors whose workers died during the outage fail
            # over NOW instead of on first use.
            rec.address = tuple(address)
            rec.resources_total = resources
            rec.labels = labels
            rec.last_heartbeat = time.monotonic()
            rec.missed_health_checks = 0
            nr = NodeResources(ResourceSet(resources), labels)
            self._resource_views[node_id] = NodeView(node_id, nr)
            self._bump_view(node_id)
            self.add_event("NODE_RECONNECTED",
                           f"node {node_id[:12]} re-registered",
                           node_id=node_id, is_head=is_head)
            if worker_ids is not None:
                await self._reconcile_node_workers(node_id,
                                                   set(worker_ids))
        else:
            rec = NodeRecord(
                node_id=node_id, address=tuple(address),
                resources_total=resources, labels=labels,
                node_index=self._next_node_index, is_head=is_head,
                session_name=self.session_name,
                last_heartbeat=time.monotonic())
            self._next_node_index += 1
            self.nodes[node_id] = rec
            nr = NodeResources(ResourceSet(resources), labels)
            self._resource_views[node_id] = NodeView(node_id, nr)
            self._bump_view(node_id)
            self.publish("NODE", {"event": "ALIVE", "node_id": node_id,
                                  "address": rec.address})
            self.add_event("NODE_ALIVE", f"node {node_id[:12]} joined",
                           node_id=node_id, is_head=is_head,
                           dedupe_key=node_id)
        self._mutate("node", node_id, rec)
        return {"node_index": rec.node_index,
                "session_name": self.session_name,
                "incarnation": self.incarnation}

    async def _reconcile_node_workers(self, node_id: str,
                                      live_workers: Set[str]):
        """Fold a re-registering raylet's worker inventory: ALIVE actors
        on that node whose worker no longer exists died while the GCS
        was down (their death report raced the outage) — fail them over
        now (restart-or-dead per budget)."""
        for record in list(self.actors.values()):
            if record.node_id == node_id and record.state == "ALIVE" \
                    and record.worker_id is not None \
                    and record.worker_id.hex() not in live_workers:
                logger.warning(
                    "actor %s lost its worker during a GCS outage; "
                    "failing over", record.actor_id.hex()[:12])
                await self._handle_actor_failure(
                    record, "worker died during GCS outage")

    async def handle_heartbeat(self, node_id: str,
                               resources_available: Dict[str, float],
                               resources_total: Dict[str, float],
                               pending_demand: Optional[List[Dict]] = None,
                               queue_ages: Optional[Dict[str, float]]
                               = None,
                               draining: Optional[bool] = None,
                               known_ver: int = -1, known_epoch: int = 0,
                               gcs_incarnation: Optional[int] = None):
        if not self._check_incarnation(gcs_incarnation):
            return {"stale_gcs": True, "incarnation": self.incarnation}
        rec = self.nodes.get(node_id)
        if rec is None:
            # Not "dead" — unknown. A GCS restarted without this node's
            # record must ask it to re-register, not to exit.
            return {"unknown": True, "incarnation": self.incarnation}
        if rec.state == "DEAD":
            return {"dead": True, "incarnation": self.incarnation}
        rec.last_heartbeat = time.monotonic()
        rec.missed_health_checks = 0
        view = self._resource_views.get(node_id)
        if view is None:
            # Node restored from a snapshot after a GCS restart: its view
            # (not persisted) is rebuilt from the first heartbeat.
            view = NodeView(node_id, NodeResources(
                ResourceSet(resources_total), rec.labels))
            self._resource_views[node_id] = view
            self._bump_view(node_id)
        changed = (view.resources.total.to_dict() != resources_total
                   or view.resources.available.to_dict()
                   != resources_available)
        if changed:
            view.resources.total = ResourceSet(resources_total)
            view.resources.available = ResourceSet(resources_available)
            self._bump_view(node_id)
        # Unmet lease demand + queue ages feed the autoscaler
        # (reference: gcs_autoscaler_state_manager.cc resource_load).
        self._pending_demand[node_id] = pending_demand or []
        self._queue_ages[node_id] = queue_ages or {}
        if draining is not None and \
                bool(draining) != bool(getattr(view, "draining", False)) \
                and time.monotonic() - \
                self._drain_view_ts.get(node_id, 0.0) > 5.0:
            # Adopt the raylet's own fence state (it survives a GCS
            # failover in the raylet's memory; the recovered view
            # starts clean) — but NOT within the grace window after WE
            # flipped the view flag: a pre-flip heartbeat in flight
            # must neither clear a just-raised fence (drain start) nor
            # re-raise a just-canceled one (the node would be excluded
            # from scheduling forever).
            view.draining = bool(draining)
            self._bump_view(node_id)
        # Reply with the cluster-view *delta* since the raylet's last known
        # version (reference: ray_syncer.h's versioned resource broadcast —
        # a stable cluster exchanges no per-node payload at all, vs the
        # O(nodes^2) traffic of full snapshots every interval). The
        # incarnation rides every ack: a raylet seeing it change knows
        # the GCS restarted and re-announces (workers, reports, view).
        reply = {"dead": False, "incarnation": self.incarnation,
                 "view": self.view_delta(known_ver, known_epoch)}
        if self._finished_jobs:
            # prune here too: without it the last job ever finished
            # would be rebroadcast (and re-reaped) every heartbeat forever
            now = time.monotonic()
            self._finished_jobs = {h: ts for h, ts
                                   in self._finished_jobs.items()
                                   if now - ts <= 600}
            if self._finished_jobs:
                reply["finished_jobs"] = list(self._finished_jobs)
        return reply

    async def handle_get_cluster_demand(self):
        """Aggregate unmet demand for the autoscaler: queued lease shapes
        per node + pending placement-group bundles
        (reference: autoscaler v2 reads GcsAutoscalerStateManager state)."""
        demands = []
        for nid, shapes in self._pending_demand.items():
            rec = self.nodes.get(nid)
            if rec is None or rec.state == "DEAD":
                continue
            demands.extend(shapes)
        pending_bundles = []
        for pg in self.pgs.values():
            if pg.state in ("PENDING", "RESCHEDULING"):
                pending_bundles.extend(pg.bundles)
        return {"task_demand": demands, "pg_demand": pending_bundles}

    def cluster_view_snapshot(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        for nid, view in self._resource_views.items():
            rec = self.nodes.get(nid)
            if rec is None or rec.state == "DEAD":
                continue
            out[nid] = {
                "address": rec.address,
                "total": view.resources.total.to_dict(),
                "available": view.resources.available.to_dict(),
                "labels": view.resources.labels,
                "draining": bool(getattr(view, "draining", False)),
            }
        return out

    # -- versioned view deltas ------------------------------------------

    def _bump_view(self, node_id: str):
        self._view_version += 1
        view = self._resource_views.get(node_id)
        if view is not None:
            view.ver = self._view_version

    def _record_view_removal(self, node_id: str):
        self._view_version += 1
        self._view_removals.append((self._view_version, node_id))
        if len(self._view_removals) > 1000:
            dropped = self._view_removals[:-1000]
            self._view_removals = self._view_removals[-1000:]
            self._removals_trimmed_ver = max(self._removals_trimmed_ver,
                                             dropped[-1][0])

    def view_delta(self, since: int, epoch: int = 0) -> Dict[str, Any]:
        """Entries changed after `since`, or a full snapshot when `since`
        predates retained removal history, comes from another GCS
        incarnation, or is -1 for a fresh raylet."""
        if since < 0 or epoch != self._view_epoch \
                or since < self._removals_trimmed_ver \
                or since > self._view_version:
            return {"full": True, "ver": self._view_version,
                    "epoch": self._view_epoch,
                    "delta": self.cluster_view_snapshot(), "removed": []}
        delta = {}
        for nid, view in self._resource_views.items():
            if getattr(view, "ver", 0) <= since:
                continue
            rec = self.nodes.get(nid)
            if rec is None or rec.state == "DEAD":
                continue
            delta[nid] = {
                "address": rec.address,
                "total": view.resources.total.to_dict(),
                "available": view.resources.available.to_dict(),
                "labels": view.resources.labels,
                "draining": bool(getattr(view, "draining", False)),
            }
        removed = [nid for ver, nid in self._view_removals if ver > since]
        return {"full": False, "ver": self._view_version,
                "epoch": self._view_epoch, "delta": delta,
                "removed": removed}

    async def handle_get_all_nodes(self):
        return [
            {
                "node_id": r.node_id, "address": r.address, "state": r.state,
                "resources": r.resources_total, "labels": r.labels,
                "node_index": r.node_index, "is_head": r.is_head,
                "session_name": r.session_name,
            }
            for r in self.nodes.values()
        ]

    async def handle_drain_node(self, node_id: str,
                                timeout_s: Optional[float] = None,
                                exit_process: bool = False,
                                migrate: bool = True,
                                cancel: bool = False):
        """GCS-coordinated graceful drain of one node (the rolling-
        upgrade / elastic-scale-in primitive; reference: the autoscaler
        drain protocol through gcs_autoscaler_state_manager):

        1. fence the node in the cluster view (schedulers and peer
           raylets stop placing work there — propagated in the next
           heartbeat's view delta),
        2. fence the raylet itself (``drain_self(phase="fence")``:
           queued lease requests spill to healthy nodes or bounce),
        3. migrate its actors — detached/named included — through the
           restart path WITHOUT consuming restart budget (drain is an
           operator action, not a failure),
        4. wait for in-flight leases (``phase="wait"``): stragglers
           past ``timeout_s`` get postmortem-tagged kills,
        5. with ``exit_process``, the raylet main exits clean and the
           node is declared dead here so its record doesn't linger
           until the health checker times it out.

        ``cancel=True`` lowers the fence instead (scale-in abort)."""
        rec = self.nodes.get(node_id)
        if rec is None or rec.state == "DEAD":
            return {"error": f"unknown or dead node {node_id[:12]}"}
        view = self._resource_views.get(node_id)
        raylet = self.clients.get(rec.address)
        if cancel:
            if view is not None and getattr(view, "draining", False):
                view.draining = False
                self._drain_view_ts[node_id] = time.monotonic()
                self._bump_view(node_id)
            try:
                await raylet.call("drain_self", phase="cancel",
                                  timeout=10)
            except Exception as e:
                return {"error": f"drain cancel rpc failed: {e}"}
            self.add_event("NODE_DRAIN_CANCELED",
                           f"drain of node {node_id[:12]} canceled",
                           node_id=node_id)
            return {"draining": False}
        if view is not None and not getattr(view, "draining", False):
            view.draining = True
            self._drain_view_ts[node_id] = time.monotonic()
            self._bump_view(node_id)
        self.add_event("NODE_DRAINING",
                       f"node {node_id[:12]} draining"
                       + (" (will exit)" if exit_process else ""),
                       severity="WARNING", node_id=node_id)
        try:
            await raylet.call("drain_self", phase="fence",
                              reason="gcs-coordinated drain", timeout=10)
        except Exception as e:
            return {"error": f"drain fence rpc failed: {e}"}
        migrated: List[str] = []
        if migrate:
            for record in list(self.actors.values()):
                if record.node_id == node_id and record.state == "ALIVE":
                    await self._migrate_actor(
                        record, f"node {node_id[:12]} draining")
                    migrated.append(record.actor_id.hex())
        budget = timeout_s if timeout_s is not None \
            else CONFIG.drain_timeout_s
        try:
            report = await raylet.call(
                "drain_self", phase="wait", timeout_s=budget,
                exit_process=exit_process, timeout=budget + 30)
        except Exception as e:
            return {"error": f"drain wait rpc failed: {e}",
                    "migrated_actors": migrated}
        if not isinstance(report, dict):
            report = {"drained": bool(report)}
        report["node_id"] = node_id
        report["migrated_actors"] = migrated
        if exit_process:
            # The raylet is exiting on purpose: retire the node record
            # now (fails over anything missed, removes it from views)
            # instead of waiting out the health-check threshold.
            await self._on_node_death(node_id,
                                      "drained for rolling restart")
        return report

    async def _migrate_actor(self, record: ActorRecord, cause: str):
        """Move one ALIVE actor off its node through the restart path
        WITHOUT consuming restart budget: publish RESTARTING first (so
        callers park new calls), kill the old instance, reschedule on a
        non-draining node. Named/detached actors keep their name — the
        PR-10 failover path re-resolves them at the new address."""
        if record.state != "ALIVE":
            return
        old_addr = record.address
        record.state = "RESTARTING"
        record.address = None
        record.node_id = None
        record.worker_id = None
        record.sched_epoch += 1
        self._publish_actor(record)
        if old_addr is not None:
            try:
                await self.clients.get(tuple(old_addr)).call(
                    "kill_actor", actor_id=record.actor_id, timeout=5)
            except Exception:
                logger.debug("kill_actor during drain migration failed "
                             "(worker already gone?)", exc_info=True)
        aio.spawn(self._schedule_actor(record), what="schedule_actor")
        self._mutate("actor", record.actor_id, record)
        logger.info("migrating actor %s: %s",
                    record.actor_id.hex()[:12], cause)

    async def handle_get_autoscaler_state(self):
        """The autoscaler state manager's view (reference:
        gcs_autoscaler_state_manager.h): per-node capacity/queue/drain
        state plus aggregate unmet demand — everything the elastic
        reconciler needs in ONE rpc."""
        demand = await self.handle_get_cluster_demand()
        nodes: Dict[str, Any] = {}
        for nid, rec in self.nodes.items():
            if rec.state == "DEAD":
                continue
            view = self._resource_views.get(nid)
            ages = self._queue_ages.get(nid, {})
            nodes[nid] = {
                "node_index": rec.node_index,
                "is_head": rec.is_head,
                "labels": rec.labels,
                "total": view.resources.total.to_dict()
                if view else rec.resources_total,
                "available": view.resources.available.to_dict()
                if view else {},
                "draining": bool(getattr(view, "draining", False)),
                "queue_depth": len(self._pending_demand.get(nid, ())),
                "queue_age_s": max(ages.values(), default=0.0),
                "queue_ages": ages,
            }
        return {"nodes": nodes,
                "task_demand": demand["task_demand"],
                "pg_demand": demand["pg_demand"]}

    async def _health_check_loop(self):
        period = CONFIG.health_check_period_s
        while True:
            try:
                await asyncio.sleep(period)
                now = time.monotonic()
                for rec in list(self.nodes.values()):
                    if rec.state == "DEAD":
                        continue
                    stale = now - rec.last_heartbeat
                    if stale > CONFIG.health_check_timeout_s:
                        rec.missed_health_checks += 1
                        # Active probe before declaring death.
                        try:
                            await self.clients.get(rec.address).call(
                                "ping", timeout=CONFIG.health_check_timeout_s)
                            rec.last_heartbeat = time.monotonic()
                            rec.missed_health_checks = 0
                        except Exception:
                            logger.debug(
                                "health ping to node %s failed (%d missed)",
                                rec.node_id[:12], rec.missed_health_checks,
                                exc_info=True)
                    if rec.missed_health_checks >= \
                            CONFIG.health_check_failure_threshold:
                        await self._on_node_death(rec.node_id, "health check failed")
                if now - self._last_driver_sweep >= \
                        CONFIG.driver_health_check_period_s:
                    self._last_driver_sweep = now
                    await self._sweep_dead_drivers()
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("gcs health check loop error")

    async def _sweep_dead_drivers(self):
        """Drivers that exit without disconnecting (crash, os._exit) must
        not strand their leases/actors/PGs forever: ping each RUNNING
        job's driver; repeated failures finish the job (reference:
        gcs_job_manager.cc marks jobs dead when the driver's RPC channel
        drops — this wire has no channel ownership, so an active probe)."""
        async def probe(rec):
            try:
                # The incarnation rides the liveness ping: a driver that
                # never noticed the restart (its calls all succeeded or
                # it was idle) learns of the new incarnation within one
                # sweep period and re-subscribes its pubsub channels.
                await self.clients.get(tuple(rec.driver_address)).call(
                    "ping", gcs_incarnation=self.incarnation,
                    timeout=CONFIG.health_check_timeout_s)
                rec.missed_pings = 0
            except (ConnectionError, ConnectionRefusedError) as e:
                # Refused/closed connection = the process is GONE (a
                # dead port refuses instantly). Timeouts are NOT strikes:
                # a flooding driver's io thread can be GIL-starved for
                # many seconds on a contended box, and killing its leases
                # mid-flood devastated the multi-client bench.
                rec.missed_pings = getattr(rec, "missed_pings", 0) + 1
                if rec.missed_pings >= \
                        CONFIG.driver_health_check_failure_threshold:
                    logger.warning("driver for job %s unreachable (%s) %d "
                                   "times; finishing job",
                                   rec.job_id.hex()[:8], e, rec.missed_pings)
                    await self._finish_job(rec.job_id)
            except Exception:
                # timeout/other: congested, not provably dead
                logger.debug("driver probe inconclusive for job %s",
                             rec.job_id.hex()[:8], exc_info=True)
        running = [rec for rec in self.jobs.values()
                   if rec.state == "RUNNING" and rec.driver_address]
        if running:
            # concurrent, with an overall bound: K stalled drivers must
            # not serialize into a K*timeout stall of the health loop
            try:
                await asyncio.wait_for(
                    asyncio.gather(*(probe(r) for r in running)),
                    CONFIG.health_check_timeout_s * 2)
            except asyncio.TimeoutError:
                pass

    async def _on_node_death(self, node_id: str, cause: str):
        rec = self.nodes.get(node_id)
        if rec is None or rec.state == "DEAD":
            return
        logger.warning("node %s declared dead: %s", node_id[:12], cause)
        rec.state = "DEAD"
        view = self._resource_views.pop(node_id, None)
        self._record_view_removal(node_id)
        self.publish("NODE", {"event": "DEAD", "node_id": node_id,
                              "address": rec.address})
        self.add_event("NODE_DEAD", f"node {node_id[:12]} dead: {cause}",
                       severity="ERROR", node_id=node_id, cause=cause)
        # Drop object locations on the dead node; owners reconstruct on demand.
        for key, (owner, locations, size) in list(self.object_dir.items()):
            locations.discard(node_id)
        # Restart or fail actors that lived there.
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in ("ALIVE",
                                                            "RESTARTING",
                                                            "PENDING"):
                await self._handle_actor_failure(actor, f"node died: {cause}")
        # Reschedule placement groups with bundles there.
        for pg in list(self.pgs.values()):
            if pg.state in ("CREATED", "PENDING") and \
                    node_id in [n for n in pg.bundle_nodes if n]:
                pg.state = "RESCHEDULING"
                aio.spawn(self._schedule_pg(pg), what="schedule_pg")
        self._mutate("node", node_id, rec)

    async def handle_report_node_death(self, node_id: str, cause: str,
                                       gcs_incarnation: Optional[int]
                                       = None):
        if not self._check_incarnation(gcs_incarnation):
            return {"stale_gcs": True}
        await self._on_node_death(node_id, cause)
        return True

    # ------------------------------------------------------------------
    # jobs
    # ------------------------------------------------------------------

    async def handle_add_job(self, driver_address: Optional[Address],
                             namespace: str,
                             metadata: Optional[Dict[str, str]] = None,
                             token: str = ""):
        if token:
            # Idempotent re-registration: a driver retrying after a lost
            # reply (GCS restart mid-call) coalesces onto its existing
            # job — no duplicate record, no second JOB_STARTED row.
            existing = self._job_tokens.get(token)
            if existing is not None:
                return existing
        self._job_counter += 1
        job_id = JobID.from_int(self._job_counter)
        rec = JobRecord(
            job_id=job_id,
            driver_address=tuple(driver_address) if driver_address else None,
            namespace=namespace, start_time=time.time(),
            metadata=metadata or {}, token=token)
        self.jobs[job_id] = rec
        if token:
            self._job_tokens[token] = job_id
        self.add_event("JOB_STARTED", f"job {job_id.hex()[:8]} started",
                       job_id=job_id.hex(), dedupe_key=job_id.hex())
        # legacy_persist=False on the counter: in legacy mode the job
        # mutate's full-snapshot write already carries it — one rewrite
        # per handler, exactly the pre-WAL cost.
        self._mutate("counter", "job_counter", self._job_counter,
                     legacy_persist=False)
        self._mutate("job", job_id, rec)
        return job_id

    async def handle_mark_job_finished(self, job_id: JobID):
        await self._finish_job(job_id)
        return True

    async def _finish_job(self, job_id: JobID):
        rec = self.jobs.get(job_id)
        if rec:
            if rec.state == "FINISHED":
                return
            rec.state = "FINISHED"
            rec.end_time = time.time()
            self.add_event("JOB_FINISHED",
                           f"job {job_id.hex()[:8]} finished",
                           job_id=job_id.hex())
        # Raylets reap the job's worker leases on their next heartbeat.
        now = time.monotonic()
        self._finished_jobs[job_id.hex()] = now
        for hex_, ts in list(self._finished_jobs.items()):
            if now - ts > 600:
                del self._finished_jobs[hex_]
        # Clean up non-detached actors owned by the job.
        for actor in list(self.actors.values()):
            if actor.spec.job_id == job_id and not actor.is_detached \
                    and actor.state != "DEAD":
                await self._kill_actor(actor, "job finished", no_restart=True)
        for pg in list(self.pgs.values()):
            if pg.creator_job == job_id and not pg.is_detached \
                    and pg.state != "REMOVED":
                await self.handle_remove_placement_group(pg.pg_id)
        if rec:
            self._mutate("job", job_id, rec)

    async def handle_get_all_jobs(self):
        return [
            {"job_id": r.job_id.hex(), "state": r.state,
             "namespace": r.namespace, "start_time": r.start_time,
             "end_time": r.end_time, "metadata": r.metadata,
             # memory_summary() queries each RUNNING driver's reference
             # table through this address
             "driver_address": r.driver_address}
            for r in self.jobs.values()
        ]

    # ------------------------------------------------------------------
    # object directory
    # ------------------------------------------------------------------

    async def handle_add_object_location(self, object_hex: str, node_id: str,
                                         size: int,
                                         owner_address: Optional[Address]):
        entry = self.object_dir.get(object_hex)
        if entry is None:
            self.object_dir[object_hex] = (
                tuple(owner_address) if owner_address else None,
                {node_id}, size)
        else:
            entry[1].add(node_id)
        return True

    async def handle_remove_object_location(self, object_hex: str,
                                            node_id: str):
        entry = self.object_dir.get(object_hex)
        if entry is not None:
            entry[1].discard(node_id)
        return True

    async def handle_get_all_object_locations(self, limit: int = 10_000):
        """State-API listing of location-tracked (plasma) objects."""
        out = []
        for object_hex, (owner, nodes, size) in self.object_dir.items():
            out.append({"object_id": object_hex, "owner": owner,
                        "nodes": sorted(nodes), "size": size,
                        "spilled": self.spilled.get(object_hex)})
            if len(out) >= limit:
                break
        return out

    async def handle_get_object_locations(self, object_hex: str):
        entry = self.object_dir.get(object_hex)
        if entry is None:
            return {"owner": None, "nodes": [], "size": 0,
                    "spilled": self.spilled.get(object_hex)}
        owner, nodes, size = entry
        live = [n for n in nodes if n in self._resource_views]
        return {"owner": owner, "nodes": live, "size": size,
                "spilled": self.spilled.get(object_hex)}

    async def handle_add_spilled_location(self, object_hex: str, path: str):
        self.spilled[object_hex] = path
        return True

    async def handle_free_object(self, object_hex: str):
        return await self.handle_free_objects([object_hex])

    async def handle_free_objects(self, object_hexes: List[str]):
        """Batched owner-side frees: one raylet notification per node for
        the whole batch (owners batch their ref-release traffic)."""
        per_node: Dict[str, List[str]] = {}
        for object_hex in object_hexes:
            entry = self.object_dir.pop(object_hex, None)
            self.spilled.pop(object_hex, None)
            if entry is not None:
                _, nodes, _ = entry
                for node_id in nodes:
                    per_node.setdefault(node_id, []).append(object_hex)
        for node_id, hexes in per_node.items():
            rec = self.nodes.get(node_id)
            if rec and rec.state == "ALIVE":
                client = self.clients.get(rec.address)
                aio.spawn(client.call(
                    "free_objects", object_hexes=hexes, timeout=5),
                    what="free_objects")
        return True

    # ------------------------------------------------------------------
    # task events (state API / timeline backend)
    # ------------------------------------------------------------------

    async def handle_add_task_events(self, events: List[Dict[str, Any]]):
        # deque(maxlen=100_000): append past capacity evicts the oldest
        # entry in O(1) instead of the old O(n) list shift per overflow.
        self.task_events.extend(events)
        return True

    async def handle_get_task_events(self, job_id: Optional[str] = None,
                                     limit: int = 10_000,
                                     since: Optional[float] = None):
        """Last `limit` task events, optionally filtered by job and by
        `since` — dashboard pollers pass their high-water timestamp
        instead of refetching the full 100k stream every poll. The
        filter keeps a 5 s slack below `since`: per-process flush
        batches land out of order across workers, and a strict cut
        would permanently drop an event flushed late (its ts below a
        high-water mark another worker already advanced). Pollers must
        fold re-delivered events idempotently (the task fold is)."""
        events = self.task_events
        if since is not None:
            # Events arrive roughly time-ordered (1 s flush batches);
            # scan from the right and stop once the old region looks
            # solid instead of walking all 100k entries per poll. The
            # stop needs a RUN of stale entries, not the first one: the
            # deque is arrival-ordered and e.g. a SPAN event carries its
            # span's START time, so one long-running span at the tail
            # would otherwise wall off every newer event behind it.
            cutoff = since - 5.0
            stale_run = 0
            out = []
            for ev in reversed(events):
                if ev.get("ts", 0.0) <= cutoff:
                    stale_run += 1
                    if stale_run >= 256:
                        break
                    continue
                stale_run = 0
                if not job_id or ev.get("job_id") == job_id:
                    out.append(ev)
                    if len(out) >= limit:
                        break
            out.reverse()
            return out
        if job_id:
            matched = [e for e in events if e.get("job_id") == job_id]
            return matched[-limit:]
        if len(events) <= limit:
            return list(events)
        return list(itertools.islice(events, len(events) - limit,
                                     len(events)))

    # ------------------------------------------------------------------
    # cluster event log (reference: the GCS-backed event table behind
    # `ray list cluster-events`; bounded, structured, persisted)
    # ------------------------------------------------------------------

    def add_event(self, event_type: str, message: str = "",
                  severity: str = "INFO",
                  dedupe_key: Optional[str] = None, **fields):
        """Append one event row. ``dedupe_key`` marks registration-type
        rows (JOB_STARTED, NODE_ALIVE, ACTOR registrations): one row per
        (type, entity) across reconnects AND restarts — the recovered
        log seeds the dedupe set, so a re-registration storm after
        failover can't double-fire them."""
        if dedupe_key is not None:
            k = (event_type, dedupe_key)
            if k in self._event_dedupe:
                return
            if len(self._event_dedupe) > 50_000:
                # Evict the oldest fifth (insertion-ordered): recent
                # entities keep their double-fire protection.
                for old in list(itertools.islice(self._event_dedupe,
                                                 10_000)):
                    del self._event_dedupe[old]
            self._event_dedupe[k] = None
        self._event_seq += 1
        ev = {"ts": time.time(), "type": event_type,
              "severity": severity, "message": message,
              "seq": self._event_seq}
        ev.update(fields)
        self.events.append(ev)
        self._mutate("event", None, ev, legacy_persist=False)

    async def handle_add_event(self, event_type: str, message: str = "",
                               severity: str = "INFO",
                               fields: Optional[Dict[str, Any]] = None):
        """External publish point (raylets report spill/restore and
        memory-pressure; workers could report their own)."""
        self.add_event(event_type, message, severity, **(fields or {}))
        return True

    async def handle_get_events(self, event_type: Optional[str] = None,
                                since: Optional[float] = None,
                                severity: Optional[str] = None,
                                limit: int = 1000):
        out = []
        for ev in reversed(self.events):
            if since is not None and ev["ts"] <= since:
                break
            if event_type and ev["type"] != event_type:
                continue
            if severity and ev["severity"] != severity:
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        out.reverse()
        return out

    # ------------------------------------------------------------------
    # SLO alert table (flight deck: bounded rows the alert engine
    # fires; each fire also lands an SLO_ALERT event so the alert is
    # visible in the ordinary event stream and its WAL persistence)
    # ------------------------------------------------------------------

    def add_alert(self, rule: str, message: str = "",
                  severity: str = "WARNING",
                  fields: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
        self._alert_seq += 1
        row = {"ts": time.time(), "rule": rule, "severity": severity,
               "message": message, "seq": self._alert_seq}
        row.update(fields or {})
        self.alerts.append(row)
        self.add_event("SLO_ALERT", message=message, severity=severity,
                       rule=rule, **(fields or {}))
        return row

    async def handle_add_alert(self, rule: str, message: str = "",
                               severity: str = "WARNING",
                               fields: Optional[Dict[str, Any]] = None):
        """External publish point — the alert engine's daemon thread
        (wherever it runs) fires through here."""
        self.add_alert(rule, message, severity, fields)
        return True

    async def handle_get_alerts(self, rule: Optional[str] = None,
                                since: Optional[float] = None,
                                severity: Optional[str] = None,
                                limit: int = 100):
        out = []
        for row in reversed(self.alerts):
            if since is not None and row["ts"] <= since:
                break
            if rule and row["rule"] != rule:
                continue
            if severity and row["severity"] != severity:
                continue
            out.append(row)
            if len(out) >= limit:
                break
        out.reverse()
        return out

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    async def handle_register_actor(self, spec: TaskSpec, name: str,
                                    namespace: str, is_detached: bool,
                                    get_if_exists: bool = False):
        actor_id = spec.actor_id
        prior = self.actors.get(actor_id)
        if prior is not None and prior.state != "DEAD":
            # Idempotent re-registration (a driver retrying a call whose
            # reply was lost across a GCS restart): the record exists —
            # return it without re-firing ACTOR_* events, scheduling a
            # second instance, or double-counting.
            return {"actor_id": actor_id, "existing": True}
        if name:
            existing_id = self.named_actors.get((namespace, name))
            if existing_id is not None:
                existing = self.actors.get(existing_id)
                if existing is not None and existing.state != "DEAD":
                    if get_if_exists:
                        return {"actor_id": existing_id, "existing": True}
                    raise ValueError(
                        f"actor name {name!r} already taken in namespace "
                        f"{namespace!r}")
        record = ActorRecord(
            actor_id=actor_id, spec=spec, name=name, namespace=namespace,
            max_restarts=spec.max_restarts, is_detached=is_detached,
            owner_address=spec.owner_address,
            placement_group_id=spec.scheduling_strategy.placement_group_id)
        self.actors[actor_id] = record
        if name:
            self.named_actors[(namespace, name)] = actor_id
            # legacy mode: the actor mutate below snapshots everything.
            self._mutate("named", (namespace, name), actor_id,
                         legacy_persist=False)
        record.sched_epoch += 1
        aio.spawn(self._schedule_actor(record), what="schedule_actor")
        self._mutate("actor", actor_id, record)
        return {"actor_id": actor_id, "existing": False}

    async def _schedule_actor(self, record: ActorRecord):
        """Pick a node, lease a worker there, push the creation task
        (reference: gcs_actor_scheduler.cc)."""
        epoch = record.sched_epoch
        spec = record.spec
        demand = ResourceSet(spec.resources)
        strategy = spec.scheduling_strategy
        deadline = time.monotonic() + 1e9  # actors wait indefinitely
        bo = Backoff(base_s=0.05, max_s=1.0, mult=1.6)
        # After a lease-RPC timeout the grant is (very likely) still in
        # flight on THAT raylet; the retry must return to the same node
        # so the idempotency key can coalesce — re-picking would strand
        # the original grant as a leaked leased worker.
        pinned_node: Optional[str] = None
        while record.state not in ("DEAD",) and record.sched_epoch == epoch:
            if pinned_node is not None and \
                    getattr(self.nodes.get(pinned_node), "state",
                            "DEAD") != "DEAD":
                node_id = pinned_node
            else:
                pinned_node = None
                async with self.actor_sched_lock:
                    node_id = self._pick_node(demand, strategy,
                                              spec.label_selector)
            if node_id is None:
                await bo.async_sleep()
                if time.monotonic() > deadline:
                    break
                continue
            rec = self.nodes.get(node_id)
            if rec is None or rec.state == "DEAD":
                continue
            raylet = self.clients.get(rec.address)
            try:
                reply = await raylet.call(
                    "request_worker_lease",
                    spec_meta={
                        "resources": spec.resources,
                        "shape_key": spec.shape_key(),
                        "runtime_env": spec.runtime_env,
                        "pg": (strategy.placement_group_id,
                               strategy.bundle_index)
                        if strategy.kind == "placement_group" else None,
                        "grant_or_reject": True,
                        "is_actor": True,
                        # idempotency key: a lease retry after an RPC
                        # timeout coalesces onto the original in-flight
                        # grant raylet-side (one worker per attempt).
                        # The epoch is part of the key: a RESTART (new
                        # epoch) must get a FRESH worker, not the dead
                        # incarnation's cached grant.
                        "actor_id": f"{spec.actor_id.hex()}:{epoch}",
                        "job": spec.job_id.hex(),
                    },
                    # Generous default: the raylet's bounded spawn
                    # pipeline may queue this grant behind hundreds of
                    # other spawns in an actor storm; a dead raylet still
                    # fails fast via the transport, and a timed-out retry
                    # coalesces onto the same grant raylet-side.
                    timeout=CONFIG.actor_lease_rpc_timeout_s)
            except Exception as e:
                logger.warning("actor lease request to %s failed: %s",
                               node_id[:12], e)
                pinned_node = node_id  # retry where the grant may live
                await bo.async_sleep()
                continue
            pinned_node = None
            if reply.get("rejected"):
                if reply.get("permanent"):
                    # deterministic env failure: creating again would fail
                    # the same way — fail the actor instead of spinning
                    if record.sched_epoch == epoch:
                        await self._handle_actor_failure(
                            record,
                            f"worker environment failed: "
                            f"{reply.get('error')}", restartable=False)
                    return
                await bo.async_sleep()
                continue
            worker_addr = tuple(reply["worker_address"])
            lease_id = reply["lease_id"]
            if record.sched_epoch != epoch or record.state == "DEAD":
                # Stale loop: give the worker back and bow out.
                aio.spawn(raylet.call(
                    "return_worker", lease_id=lease_id, dispose=True,
                    timeout=10), what="return_worker")
                return
            # Push the creation task directly to the leased worker. Bounded:
            # a worker wedged inside a pathological __init__ (alive, never
            # replying) must fail the creation and reschedule, not hang
            # actor scheduling forever.
            try:
                worker = self.clients.get(worker_addr)
                result = await worker.call(
                    "push_task", spec=spec, lease_id=lease_id,
                    timeout=CONFIG.actor_creation_timeout_s)
            except Exception as e:
                # Dispose the (possibly wedged) worker and free its lease —
                # a gang-reserved slice must not stay held by a failed
                # creation attempt or the restart can never place.
                aio.spawn(raylet.call(
                    "return_worker", lease_id=lease_id, dispose=True,
                    timeout=10), what="return_worker")
                if record.sched_epoch == epoch:
                    await self._handle_actor_failure(
                        record, f"creation task push failed: {e}")
                return
            if record.sched_epoch != epoch or record.state == "DEAD":
                aio.spawn(raylet.call(
                    "return_worker", lease_id=lease_id, dispose=True,
                    timeout=10), what="return_worker")
                return
            if result.get("error") is not None:
                if "double-granted lease" in str(result["error"]):
                    # The worker refused because it already hosts another
                    # actor — a scheduling artifact, not a user failure:
                    # dispose this grant and re-place WITHOUT consuming
                    # the actor's restart budget.
                    logger.warning(
                        "actor %s creation hit a double-granted worker "
                        "on %s; rescheduling", spec.actor_id.hex()[:12],
                        node_id[:12])
                    aio.spawn(raylet.call(
                        "return_worker", lease_id=lease_id, dispose=True,
                        timeout=10), what="return_worker")
                    if record.sched_epoch == epoch and \
                            record.state != "DEAD":
                        record.sched_epoch += 1
                        aio.spawn(self._schedule_actor(record),
                                  what="schedule_actor")
                    return
                record.state = "DEAD"
                record.death_cause = f"creation failed: {result['error']}"
                self._publish_actor(record)
                self._mutate("actor", record.actor_id, record)
                return
            record.state = "ALIVE"
            record.address = worker_addr
            record.node_id = node_id
            record.worker_id = reply.get("worker_id")
            self._publish_actor(record)
            self._mutate("actor", record.actor_id, record)
            return

    def _pick_node(self, demand: ResourceSet, strategy,
                   label_selector) -> Optional[str]:
        view = self._resource_views
        if strategy.kind == "placement_group" and strategy.placement_group_id:
            pg = self.pgs.get(strategy.placement_group_id)
            if pg is None or pg.state != "CREATED":
                return None
            index = strategy.bundle_index if strategy.bundle_index >= 0 else 0
            return pg.bundle_nodes[index]
        if strategy.kind == "node_affinity":
            return pick_node_affinity(view, demand, strategy.node_id,
                                      strategy.soft)
        if strategy.kind == "node_label" or label_selector:
            selector = dict(strategy.label_selector or {})
            selector.update(label_selector or {})
            return pick_node_label(view, demand, selector)
        if strategy.kind == "SPREAD":
            self._spread_clock += 1
            return pick_spread(view, demand, self._spread_clock)
        head = next((n for n in self.nodes.values() if n.is_head), None)
        local = head.node_id if head else ""
        node = pick_hybrid(view, demand, local_node_id=local)
        return node

    def _publish_actor(self, record: ActorRecord):
        # The existing publish point doubles as the event-log feed:
        # every externally visible actor state transition lands one row.
        self.add_event(
            f"ACTOR_{record.state}",
            f"actor {record.actor_id.hex()[:12]} "
            f"({record.spec.function.qualname}) -> {record.state}"
            + (f": {record.death_cause}" if record.death_cause else ""),
            severity="ERROR" if record.state == "DEAD" else "INFO",
            actor_id=record.actor_id.hex(), node_id=record.node_id,
            num_restarts=record.num_restarts,
            death_cause=record.death_cause or None)
        self.publish("ACTOR", {
            "actor_id": record.actor_id,
            "state": record.state,
            "address": record.address,
            "node_id": record.node_id,
            "num_restarts": record.num_restarts,
            # Instance token: bumps on EVERY (re)schedule — including
            # budget-free drain migrations, where num_restarts does not
            # move. Callers renumber their sequence stream when it
            # changes (a fresh instance expects seq 0).
            "instance": record.sched_epoch,
            "death_cause": record.death_cause,
        })

    async def _handle_actor_failure(self, record: ActorRecord, cause: str,
                                    restartable: bool = True):
        if record.state == "DEAD":
            return
        unlimited = record.max_restarts == -1
        if restartable and \
                (unlimited or record.num_restarts < record.max_restarts):
            record.num_restarts += 1
            record.state = "RESTARTING"
            record.address = None
            record.node_id = None
            record.sched_epoch += 1
            self._publish_actor(record)
            aio.spawn(self._schedule_actor(record), what="schedule_actor")
        else:
            record.state = "DEAD"
            record.death_cause = cause
            self._publish_actor(record)
            if record.name:
                self.named_actors.pop((record.namespace, record.name), None)
                # legacy mode: the actor mutate below snapshots it all.
                self._mutate("named", (record.namespace, record.name),
                             None, legacy_persist=False)
        self._mutate("actor", record.actor_id, record)

    async def handle_report_actor_failure(self, actor_id: ActorID,
                                          cause: str):
        record = self.actors.get(actor_id)
        if record is not None:
            await self._handle_actor_failure(record, cause)
        return True

    async def handle_report_worker_death(self, node_id: str, worker_id: bytes,
                                         cause: str,
                                         postmortem: Optional[Dict[str,
                                                                   Any]]
                                         = None,
                                         gcs_incarnation: Optional[int]
                                         = None):
        """Raylet tells us a worker process died; fail any actor on it.
        The raylet's postmortem (exit taxonomy + last captured lines)
        is retained for crashing callers (`get_worker_postmortem`),
        attached to the WORKER_DIED event, and folded into the death
        cause so ActorDiedError carries the actor's last words."""
        if not self._check_incarnation(gcs_incarnation):
            return {"stale_gcs": True}
        from . import logplane
        whex = worker_id.hex()
        summary = logplane.summarize_postmortem(postmortem)
        exit_info = (postmortem or {}).get("exit") or {}
        if postmortem is not None:
            self.worker_postmortems[whex] = postmortem
            while len(self.worker_postmortems) > 200:
                self.worker_postmortems.popitem(last=False)
        self.add_event("WORKER_DIED",
                       f"worker {whex[:12]} on node "
                       f"{node_id[:12]} died: {cause}"
                       + (f" ({summary})" if summary else ""),
                       severity="WARNING", node_id=node_id,
                       worker_id=whex, cause=cause,
                       exit_kind=exit_info.get("kind"),
                       postmortem=postmortem)
        if summary:
            cause = f"{cause} ({summary})"
        for record in list(self.actors.values()):
            if record.worker_id == worker_id and record.state == "ALIVE":
                await self._handle_actor_failure(record, cause)
        return True

    async def handle_get_worker_postmortem(self, worker_hex: str):
        """The retained postmortem of one dead worker (None while the
        raylet's death report has not landed yet — callers poll
        briefly)."""
        return self.worker_postmortems.get(worker_hex)

    async def _kill_actor(self, record: ActorRecord, cause: str,
                          no_restart: bool):
        if record.address is not None:
            try:
                await self.clients.get(record.address).call(
                    "kill_actor", actor_id=record.actor_id, timeout=5)
            except Exception:
                logger.debug("kill_actor RPC to %s failed (worker already "
                             "dead?)", record.address, exc_info=True)
        if no_restart:
            record.max_restarts = record.num_restarts  # exhaust budget
        await self._handle_actor_failure(record, cause)

    async def handle_kill_actor(self, actor_id: ActorID,
                                no_restart: bool = True):
        record = self.actors.get(actor_id)
        if record is None:
            return False
        await self._kill_actor(record, "killed via kill()",
                               no_restart=no_restart)
        return True

    async def handle_actor_exited(self, actor_id: ActorID, cause: str = ""):
        """Graceful exit (__ray_terminate__); never restarted."""
        record = self.actors.get(actor_id)
        if record is None:
            return False
        record.max_restarts = record.num_restarts
        await self._handle_actor_failure(record, cause or "actor exited")
        return True

    async def handle_get_actor_info(self, actor_id: Optional[ActorID] = None,
                                    name: str = "", namespace: str = ""):
        if actor_id is None and name:
            actor_id = self.named_actors.get((namespace, name))
            if actor_id is None:
                return None
        record = self.actors.get(actor_id)
        if record is None:
            return None
        return {
            "actor_id": record.actor_id, "state": record.state,
            "address": record.address, "node_id": record.node_id,
            "name": record.name, "namespace": record.namespace,
            "num_restarts": record.num_restarts,
            "instance": record.sched_epoch,
            "death_cause": record.death_cause,
            "is_detached": record.is_detached,
            "class_name": record.spec.function.qualname,
        }

    async def handle_list_named_actors(self, namespace: str = "",
                                       all_namespaces: bool = False):
        out = []
        for (ns, name), actor_id in self.named_actors.items():
            if all_namespaces or ns == namespace:
                out.append({"name": name, "namespace": ns})
        return out

    async def handle_get_all_actors(self):
        return [await self.handle_get_actor_info(actor_id=a)
                for a in self.actors]

    # ------------------------------------------------------------------
    # placement groups (two-phase prepare/commit,
    # reference: gcs_placement_group_scheduler.h:135-211)
    # ------------------------------------------------------------------

    async def handle_create_placement_group(
            self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
            strategy: str, name: str, creator_job: Optional[JobID],
            is_detached: bool = False):
        if pg_id in self.pgs:
            # Idempotent re-registration after a reconnect/lost reply.
            return True
        record = PlacementGroupRecord(
            pg_id=pg_id, bundles=bundles, strategy=strategy, name=name,
            creator_job=creator_job, is_detached=is_detached,
            bundle_nodes=[None] * len(bundles))
        self.pgs[pg_id] = record
        aio.spawn(self._schedule_pg(record), what="schedule_pg")
        self._mutate("pg", pg_id, record)
        return True

    async def _schedule_pg(self, record: PlacementGroupRecord):
        demand = [ResourceSet(b) for b in record.bundles]
        bo = Backoff(base_s=0.05, max_s=1.0, mult=1.6)
        # Rescheduling after a node death: release the surviving nodes'
        # reservations first, else their capacity leaks (and STRICT
        # strategies can become permanently infeasible).
        if any(n is not None for n in record.bundle_nodes):
            await self._cancel_bundles(record)
        while record.state in ("PENDING", "RESCHEDULING"):
            placement = place_bundles(self._resource_views, demand,
                                      record.strategy)
            if placement is None:
                await bo.async_sleep()
                continue
            ok = await self._try_place(record, placement)
            if ok:
                record.state = "CREATED"
                record.bundle_nodes = placement
                self.publish("PG", {"pg_id": record.pg_id,
                                    "state": "CREATED",
                                    "bundle_nodes": placement})
                self._mutate("pg", record.pg_id, record)
                return
            await bo.async_sleep()

    async def _try_place(self, record: PlacementGroupRecord,
                         placement: List[str]) -> bool:
        # Phase 1: prepare every bundle on its raylet.
        prepared: List[Tuple[str, int]] = []
        for index, node_id in enumerate(placement):
            rec = self.nodes.get(node_id)
            if rec is None or rec.state == "DEAD":
                break
            try:
                ok = await self.clients.get(rec.address).call(
                    "prepare_bundle", pg_id=record.pg_id, bundle_index=index,
                    resources=record.bundles[index], timeout=10)
            except Exception:
                ok = False
            if not ok:
                break
            prepared.append((node_id, index))
        if len(prepared) != len(placement):
            # Roll back phase 1.
            for node_id, index in prepared:
                rec = self.nodes.get(node_id)
                if rec and rec.state == "ALIVE":
                    try:
                        await self.clients.get(rec.address).call(
                            "cancel_bundle", pg_id=record.pg_id,
                            bundle_index=index, timeout=10)
                    except Exception:
                        logger.debug("cancel_bundle rollback on %s failed",
                                     node_id[:12], exc_info=True)
            return False
        # Phase 2: commit.
        for node_id, index in prepared:
            rec = self.nodes.get(node_id)
            try:
                await self.clients.get(rec.address).call(
                    "commit_bundle", pg_id=record.pg_id, bundle_index=index,
                    timeout=10)
            except Exception:
                logger.warning("pg commit failed on %s", node_id[:12])
        return True

    async def _cancel_bundles(self, record: PlacementGroupRecord):
        for index, node_id in enumerate(record.bundle_nodes):
            if node_id is None:
                continue
            rec = self.nodes.get(node_id)
            if rec and rec.state == "ALIVE":
                try:
                    await self.clients.get(rec.address).call(
                        "cancel_bundle", pg_id=record.pg_id,
                        bundle_index=index, timeout=10)
                except Exception:
                    logger.debug("cancel_bundle on %s failed (node "
                                 "leaving?)", node_id[:12], exc_info=True)
        record.bundle_nodes = [None] * len(record.bundles)

    async def handle_remove_placement_group(self, pg_id: PlacementGroupID):
        record = self.pgs.get(pg_id)
        if record is None:
            return False
        record.state = "REMOVED"
        # Kill actors scheduled into this group.
        for actor in list(self.actors.values()):
            if actor.placement_group_id == pg_id and actor.state != "DEAD":
                await self._kill_actor(actor, "placement group removed",
                                       no_restart=True)
        await self._cancel_bundles(record)
        self.publish("PG", {"pg_id": pg_id, "state": "REMOVED",
                            "bundle_nodes": []})
        self._mutate("pg", pg_id, record)
        return True

    async def handle_get_placement_group(self, pg_id: Optional[PlacementGroupID] = None,
                                         name: str = ""):
        record = None
        if pg_id is not None:
            record = self.pgs.get(pg_id)
        elif name:
            record = next((p for p in self.pgs.values() if p.name == name),
                          None)
        if record is None:
            return None
        return {"pg_id": record.pg_id, "state": record.state,
                "bundles": record.bundles, "strategy": record.strategy,
                "bundle_nodes": record.bundle_nodes, "name": record.name}

    async def handle_get_all_placement_groups(self):
        return [await self.handle_get_placement_group(pg_id=p)
                for p in self.pgs]

    async def handle_wait_placement_group_ready(self, pg_id: PlacementGroupID,
                                                timeout_s: float = -1):
        deadline = None if timeout_s < 0 else time.monotonic() + timeout_s
        while True:
            record = self.pgs.get(pg_id)
            if record is None:
                raise PlacementGroupError(f"placement group {pg_id} not found")
            if record.state == "CREATED":
                return True
            if record.state == "REMOVED":
                raise PlacementGroupError(f"placement group {pg_id} removed")
            if deadline is not None and time.monotonic() > deadline:
                return False
            await asyncio.sleep(0.01)

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------

    async def handle_ping(self):
        return "pong"

    async def handle_gcs_info(self):
        """Identity + durability status: the probe target for reconnect
        loops (cheap, side-effect free) and the `cli chaos` / dashboard
        failover surface."""
        return {
            "incarnation": self.incarnation,
            "session_name": self.session_name,
            "pid": os.getpid(),
            "persist_mode": self._persist_mode,
            "persist_path": self.persist_path,
            "wal_bytes": self._store.wal.size if self._store else 0,
            "failovers": self._failovers,
            "persist_fail_streak": self._persist_fail_streak,
        }

    # -- chaos harness (cli chaos / tests) -----------------------------

    async def handle_set_chaos(self, spec: str = "", seed: int = 0,
                               schedule: Optional[str] = None):
        from . import chaos
        return await chaos.handle_set_chaos(spec=spec, seed=seed,
                                            schedule=schedule)

    async def handle_chaos_kill_self(self):
        """`cli chaos kill-gcs`: SIGKILL this GCS process (the headline
        failover drill). Gated — a production cluster must opt in via
        RTPU_CHAOS_ALLOW_KILL=1."""
        if not CONFIG.chaos_allow_kill:
            raise PermissionError(
                "chaos kill refused: set RTPU_CHAOS_ALLOW_KILL=1 on the "
                "GCS process to allow it")
        from . import chaos
        loop = asyncio.get_running_loop()
        # Reply first, die a beat later.
        loop.call_later(0.05, chaos.kill_pid, os.getpid())
        return {"pid": os.getpid()}

    # -- continuous profiler (the GCS process is part of the fleet:
    # profile_cluster samples it like any worker/raylet) ---------------

    async def handle_start_profiling(self, hz: Optional[float] = None,
                                     ring_size: Optional[int] = None):
        from . import profiler
        return profiler.start_profiling(hz=hz, ring_size=ring_size)

    async def handle_stop_profiling(self):
        from . import profiler
        return profiler.stop_profiling()

    async def handle_get_profile(self, clear: bool = True,
                                 stop: bool = False):
        from . import profiler
        report = profiler.get_profile(clear=clear, stop=stop)
        report["component"] = "gcs"
        return report

    async def handle_profiling_status(self):
        from . import profiler
        return dict(profiler.profiling_status(), component="gcs")

    async def handle_dump_stacks(self, quiet: bool = True):
        from . import profiler
        # pid included so fleet sweeps can dedupe the shared local-mode
        # process by (host, pid)
        return {"pid": os.getpid(), "text": profiler.stack_dump_text(
            asyncio_tasks=asyncio.all_tasks())}

    async def handle_get_cluster_view(self):
        return self.cluster_view_snapshot()
