"""Client to the GCS (reference: src/ray/gcs_client/ + GlobalStateAccessor).

Thin async wrappers plus sync bridges for user-thread callers. Subscription
delivery rides the process's own RpcServer: the GCS pushes `pubsub_message`
RPCs at us and we fan out to registered callbacks.

Failover: the client tracks the GCS **incarnation** (stamped by the
server, bumped on every restart). A restart is detected two ways —
transport failures trigger a jittered-backoff probe loop against
`gcs_info`, and the GCS's own driver-liveness pings piggyback the
current incarnation (see `CoreWorker.handle_ping`). On a new
incarnation the client re-subscribes every pubsub channel it holds
(subscriptions are server-side soft state, lost with the old process)
and fires registered reconnect hooks so owners can replay in-flight
state. `reconnecting_call` additionally rides individual calls through
a restart window (bounded by `gcs_reconnect_timeout_s`) for callers
that must not fail across a failover (actor registration, subscribe).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .backoff import Backoff
from .config import CONFIG
from .errors import RpcError
from .rpc import (DEFAULT_TIMEOUT, Address, EventLoopThread, RpcClient,
                  RpcServer)

logger = logging.getLogger(__name__)

# Transport-level failures that may mean "the GCS is restarting".
# Deliberately NARROW: a handler's own exception crosses the wire as its
# original type, and e.g. a PermissionError (an OSError subclass, raised
# by the gated chaos kill) must fail immediately, not spin the 60s
# reconnect window. Raw socket errors surface as ConnectionError/
# RpcError from the rpc layer; a server-side RpcError ("no handler") is
# the one accepted ambiguity (version skew is transient during a rolling
# head upgrade).
_TRANSPORT_ERRORS = (RpcError, ConnectionError, asyncio.TimeoutError)


class GcsClient:
    def __init__(self, gcs_address: Address,
                 local_server: Optional[RpcServer] = None):
        self.address = tuple(gcs_address)
        self.client = RpcClient(self.address)
        self._local_server = local_server
        self._subs_lock = threading.Lock()
        self._subscriptions: Dict[str, List[Callable]] = {}
        if local_server is not None:
            local_server.register("pubsub_message", self._on_pubsub_message)
        # Failover state: the incarnation we last saw, a single-flight
        # probe guard, and hooks run after a reconnect (owners replay
        # in-flight state: actor submitters reconcile, etc.).
        self._incarnation: Optional[int] = None
        self._probe_running = False
        self._probe_lock = threading.Lock()
        self._down_since: Optional[float] = None
        self._reconnect_hooks: List[Callable] = []
        self._closed = False

    # -- async core ------------------------------------------------------

    async def call(self, method: str, **kwargs) -> Any:
        try:
            return await self.client.call(
                method, retries=CONFIG.rpc_max_retries, **kwargs)
        except _TRANSPORT_ERRORS:
            self._note_failure()
            raise

    def call_sync(self, method: str,
                  timeout: Optional[float] = DEFAULT_TIMEOUT,
                  **kwargs) -> Any:
        try:
            return self.client.call_sync(
                method, timeout=timeout, retries=CONFIG.rpc_max_retries,
                **kwargs)
        except _TRANSPORT_ERRORS:
            self._note_failure()
            raise

    async def reconnecting_call(self, method: str,
                                timeout: Optional[float] = DEFAULT_TIMEOUT,
                                **kwargs) -> Any:
        """`call`, but riding through a GCS restart: transport failures
        retry on a jittered-exponential schedule until
        `gcs_reconnect_timeout_s` is exhausted (0 = behave like call).
        Use only for idempotent calls — the server may have executed an
        attempt whose reply was lost (registration paths dedupe
        server-side for exactly this reason)."""
        window = CONFIG.gcs_reconnect_timeout_s
        if not window:
            return await self.call(method, timeout=timeout, **kwargs)
        bo = Backoff(base_s=CONFIG.gcs_reconnect_base_delay_ms / 1000.0,
                     max_s=CONFIG.gcs_reconnect_max_delay_ms / 1000.0,
                     deadline_s=window,
                     site="gcs_reconnecting_call")
        while True:
            try:
                return await self.client.call(
                    method, timeout=timeout,
                    retries=CONFIG.rpc_max_retries, **kwargs)
            except _TRANSPORT_ERRORS:
                self._note_failure()
                if not await bo.async_sleep():
                    raise

    def call_sync_reconnecting(self, method: str,
                               timeout: Optional[float] = DEFAULT_TIMEOUT,
                               **kwargs) -> Any:
        """Sync bridge for reconnecting_call (user-thread callers that
        must survive a GCS failover, e.g. actor registration)."""
        per_call = CONFIG.rpc_call_timeout_s if timeout is DEFAULT_TIMEOUT \
            else (timeout or 60.0)
        total = (CONFIG.gcs_reconnect_timeout_s or 0.0) + per_call + 10.0
        return EventLoopThread.get().run_sync(
            self.reconnecting_call(method, timeout=timeout, **kwargs),
            timeout=total)

    # -- failover detection ----------------------------------------------

    def suppress_reconnect(self):
        """Shutdown is beginning: call failures are expected and must
        not spawn probe tasks that outlive the process's useful life."""
        self._closed = True

    def _note_failure(self):
        """A transport failure MAY mean the GCS is restarting: start the
        (single-flight) incarnation probe so subscriptions re-establish
        the moment a live incarnation answers."""
        if self._closed:
            return
        with self._probe_lock:
            if self._probe_running:
                return
            self._probe_running = True
            if self._down_since is None:
                self._down_since = time.monotonic()
        try:
            EventLoopThread.get().post(self._probe_reconnect())
        except RuntimeError:
            with self._probe_lock:
                self._probe_running = False

    def note_incarnation(self, incarnation: int):
        """Piggybacked incarnation observation (the GCS's driver-liveness
        ping carries it): detects a restart even when no call of ours
        ever failed. Schedules re-subscription when it changed."""
        if self._incarnation is None:
            self._incarnation = incarnation
            return
        if incarnation != self._incarnation:
            self._note_failure()

    async def _probe_reconnect(self):
        """Single-flight probe: poll gcs_info with backoff until a live
        incarnation answers (bounded by gcs_reconnect_timeout_s), then
        re-subscribe + fire hooks if the incarnation changed."""
        bo = Backoff(base_s=CONFIG.gcs_reconnect_base_delay_ms / 1000.0,
                     max_s=CONFIG.gcs_reconnect_max_delay_ms / 1000.0,
                     deadline_s=CONFIG.gcs_reconnect_timeout_s or None,
                     site="gcs_probe")
        try:
            await self._probe_reconnect_inner(bo)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("gcs reconnect probe failed unexpectedly")
        finally:
            with self._probe_lock:
                self._probe_running = False
                self._down_since = None

    async def _probe_reconnect_inner(self, bo: Backoff):
        while True:
            try:
                info = await self.client.call(
                    "gcs_info",
                    timeout=CONFIG.health_check_timeout_s)
                break
            except _TRANSPORT_ERRORS:
                if not await bo.async_sleep():
                    logger.warning(
                        "gcs unreachable for %.0fs; giving up the "
                        "reconnect probe (a later call retriggers "
                        "it)", CONFIG.gcs_reconnect_timeout_s)
                    return
        incarnation = info.get("incarnation")
        down_for = (time.monotonic() - self._down_since
                    if self._down_since else 0.0)
        if self._incarnation is None or incarnation != self._incarnation:
            # Changed incarnation = restart. An UNKNOWN baseline (a
            # worker process whose client was never seeded) must be
            # treated the same: the failure that armed this probe may
            # have been a restart, and re-subscribing on a live GCS is
            # idempotent — skipping it would silently orphan every
            # pubsub channel this process holds.
            logger.warning(
                "gcs reconnected (incarnation %s -> %s, unreachable "
                "%.2fs); re-subscribing %d channel(s)",
                self._incarnation, incarnation, down_for,
                len(self._subscriptions))
            # Adopt the new incarnation only AFTER resubscription lands:
            # adopting first would make a failed resubscribe permanent
            # (every later probe/ping would see a matching incarnation
            # and skip it — the channel stays orphaned until the next
            # restart).
            while not await self._resubscribe_all():
                if not await bo.async_sleep():
                    logger.warning(
                        "re-subscription after GCS restart did not "
                        "complete; leaving the old incarnation so a "
                        "later probe retries")
                    return
            await self._run_reconnect_hooks()
            self._incarnation = incarnation
            from .runtime_metrics import runtime_metrics
            metrics = runtime_metrics()
            metrics.gcs_reconnects.inc(tags={"component": "driver"})
            metrics.gcs_reconnect_latency.observe(
                down_for, tags={"component": "driver"})

    def add_reconnect_hook(self, hook: Callable):
        """Register a callable (sync or async, no args) run after the
        client re-establishes itself on a new GCS incarnation."""
        self._reconnect_hooks.append(hook)

    async def _run_reconnect_hooks(self):
        for hook in list(self._reconnect_hooks):
            try:
                result = hook()
                if hasattr(result, "__await__"):
                    await result
            except Exception:
                logger.exception("gcs reconnect hook failed")

    async def _resubscribe_all(self) -> bool:
        """Subscriptions are GCS-side soft state: re-issue them against
        the new incarnation so pubsub (actor updates, logs) resumes.
        Returns False when any channel failed (the caller retries)."""
        if self._local_server is None \
                or self._local_server.address is None:
            return True
        with self._subs_lock:
            channels = list(self._subscriptions)
        ok = True
        for channel in channels:
            try:
                await self.client.call(
                    "subscribe", channel=channel,
                    address=self._local_server.address,
                    retries=CONFIG.rpc_max_retries)
            except Exception:
                ok = False
                logger.warning("re-subscribe of %r after GCS restart "
                               "failed", channel, exc_info=True)
        return ok

    # -- pubsub ----------------------------------------------------------

    async def _on_pubsub_message(self, channel: str, message: Dict[str, Any]):
        with self._subs_lock:
            callbacks = list(self._subscriptions.get(channel, ()))
        for cb in callbacks:
            try:
                result = cb(message)
                if hasattr(result, "__await__"):
                    await result
            except Exception:
                logger.exception("pubsub callback failed on %s", channel)
        return True

    async def subscribe(self, channel: str, callback: Callable):
        if self._local_server is None or self._local_server.address is None:
            raise RuntimeError("subscription requires a local rpc server")
        with self._subs_lock:
            first = channel not in self._subscriptions
            self._subscriptions.setdefault(channel, []).append(callback)
        if first:
            await self.reconnecting_call(
                "subscribe", channel=channel,
                address=self._local_server.address)

    # -- KV (sync surface used by FunctionManager etc.) -------------------

    def put(self, ns: str, key: str, value: bytes, overwrite: bool = True):
        return self.call_sync("kv_put", ns=ns, key=key, value=value,
                              overwrite=overwrite)

    def get(self, ns: str, key: str) -> Optional[bytes]:
        return self.call_sync("kv_get", ns=ns, key=key)

    def delete(self, ns: str, key: str) -> bool:
        return self.call_sync("kv_del", ns=ns, key=key)

    def keys(self, ns: str, prefix: str = "") -> List[str]:
        return self.call_sync("kv_keys", ns=ns, prefix=prefix)

    def exists(self, ns: str, key: str) -> bool:
        return self.call_sync("kv_exists", ns=ns, key=key)
