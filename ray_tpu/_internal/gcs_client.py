"""Client to the GCS (reference: src/ray/gcs_client/ + GlobalStateAccessor).

Thin async wrappers plus sync bridges for user-thread callers. Subscription
delivery rides the process's own RpcServer: the GCS pushes `pubsub_message`
RPCs at us and we fan out to registered callbacks.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional

from .config import CONFIG
from .rpc import (DEFAULT_TIMEOUT, Address, EventLoopThread, RpcClient,
                  RpcServer)

logger = logging.getLogger(__name__)


class GcsClient:
    def __init__(self, gcs_address: Address,
                 local_server: Optional[RpcServer] = None):
        self.address = tuple(gcs_address)
        self.client = RpcClient(self.address)
        self._local_server = local_server
        self._subs_lock = threading.Lock()
        self._subscriptions: Dict[str, List[Callable]] = {}
        if local_server is not None:
            local_server.register("pubsub_message", self._on_pubsub_message)

    # -- async core ------------------------------------------------------

    async def call(self, method: str, **kwargs) -> Any:
        return await self.client.call(
            method, retries=CONFIG.rpc_max_retries, **kwargs)

    def call_sync(self, method: str,
                  timeout: Optional[float] = DEFAULT_TIMEOUT,
                  **kwargs) -> Any:
        return self.client.call_sync(
            method, timeout=timeout, retries=CONFIG.rpc_max_retries, **kwargs)

    # -- pubsub ----------------------------------------------------------

    async def _on_pubsub_message(self, channel: str, message: Dict[str, Any]):
        with self._subs_lock:
            callbacks = list(self._subscriptions.get(channel, ()))
        for cb in callbacks:
            try:
                result = cb(message)
                if hasattr(result, "__await__"):
                    await result
            except Exception:
                logger.exception("pubsub callback failed on %s", channel)
        return True

    async def subscribe(self, channel: str, callback: Callable):
        if self._local_server is None or self._local_server.address is None:
            raise RuntimeError("subscription requires a local rpc server")
        with self._subs_lock:
            first = channel not in self._subscriptions
            self._subscriptions.setdefault(channel, []).append(callback)
        if first:
            await self.call("subscribe", channel=channel,
                            address=self._local_server.address)

    # -- KV (sync surface used by FunctionManager etc.) -------------------

    def put(self, ns: str, key: str, value: bytes, overwrite: bool = True):
        return self.call_sync("kv_put", ns=ns, key=key, value=value,
                              overwrite=overwrite)

    def get(self, ns: str, key: str) -> Optional[bytes]:
        return self.call_sync("kv_get", ns=ns, key=key)

    def delete(self, ns: str, key: str) -> bool:
        return self.call_sync("kv_del", ns=ns, key=key)

    def keys(self, ns: str, prefix: str = "") -> List[str]:
        return self.call_sync("kv_keys", ns=ns, prefix=prefix)

    def exists(self, ns: str, key: str) -> bool:
        return self.call_sync("kv_exists", ns=ns, key=key)
