"""Standalone GCS process entrypoint (reference: gcs_server main via
`ray start --head`). Runs the head's control plane as its own process so
it can be killed and restarted independently of raylets and drivers —
the deployment shape the failover machinery (WAL + snapshot recovery,
client reconnect-and-replay) is built for, and the process the chaos
harness `kill -9`s in tests/test_gcs_failover.py.

A fixed --port keeps the address stable across restarts (clients
reconnect; no rediscovery needed). --persist-path points at the durable
store; RTPU_GCS_PERSIST selects wal/legacy/off."""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal


def main(argv=None):
    # Before any ray_tpu lock is constructed in this process.
    from .lint import sanitizer as _sanitizer
    _sanitizer.enable_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session", required=True)
    parser.add_argument("--persist-path", default="")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="[gcs] %(levelname)s %(name)s: %(message)s")

    from .gcs import GcsServer

    gcs = GcsServer(args.session,
                    persist_path=args.persist_path or None)

    async def run():
        address = await gcs.start(args.host, args.port)
        # readiness protocol line tests/tools wait on
        print(f"RTPU_GCS_READY {address[0]}:{address[1]} "  # stdout ok: protocol
              f"incarnation={gcs.incarnation}", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await gcs.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
