"""Durable GCS storage: write-ahead log + compacted snapshot.

Replaces the old whole-state ``_persist()``-per-mutation (one full
snapshot write for every actor update) with the classic WAL design the
reference gets from Redis persistence (PAPER §GCS fault tolerance):

- Mutations append one typed record to an append-only log
  (``<path>.wal``): ``u32 len | u32 crc32 | payload`` where payload is
  the pickled ``(kind, key, value)`` triple. Appends are O(record), not
  O(state).
- A compactor periodically folds the log into the snapshot file
  (``<path>``, atomic tmp+rename) and truncates the log.
- Recovery = load snapshot + replay the WAL tail. The length+checksum
  framing detects torn writes (a crash mid-append): replay stops at the
  first bad frame and ``open_append`` truncates the tail so new records
  never land after garbage.

Durability contract: records are flushed to the OS on every append;
``fsync`` is group-committed (one per event-loop tick batch, see
``GcsServer._wal_sync_soon``) unless the caller syncs explicitly.
``RTPU_GCS_PERSIST=legacy|wal|off`` selects this path, the old
whole-snapshot path, or nothing (gcs.py reads the flag; this module is
mode-agnostic storage).
"""

from __future__ import annotations

import logging
import os
import struct
import zlib
from typing import Any, Iterator, List, Optional, Tuple

from . import serialization

logger = logging.getLogger(__name__)

_REC_HDR = struct.Struct("<II")     # u32 payload_len | u32 crc32(payload)
_MAX_RECORD = 256 * 1024 * 1024     # sanity bound on one record


def encode_record(kind: str, key: Any, value: Any) -> bytes:
    payload = serialization.dumps((kind, key, value))
    return _REC_HDR.pack(len(payload), zlib.crc32(payload)) + payload


def scan_records(data: bytes) -> Tuple[List[Tuple[str, Any, Any]], int]:
    """Decode records from a WAL byte string. Returns (records,
    clean_length): replay stops at the first torn/corrupt frame and
    ``clean_length`` is the offset of the last fully valid record — the
    caller truncates there before appending."""
    records: List[Tuple[str, Any, Any]] = []
    off, n = 0, len(data)
    while n - off >= _REC_HDR.size:
        length, crc = _REC_HDR.unpack_from(data, off)
        if length > _MAX_RECORD or n - off - _REC_HDR.size < length:
            break  # torn tail: the append died mid-write
        payload = data[off + _REC_HDR.size:off + _REC_HDR.size + length]
        if zlib.crc32(payload) != crc:
            logger.warning("gcs wal: checksum mismatch at offset %d; "
                           "discarding the tail", off)
            break
        try:
            records.append(serialization.loads(payload))
        except Exception:
            logger.exception("gcs wal: undecodable record at offset %d; "
                             "discarding the tail", off)
            break
        off += _REC_HDR.size + length
    return records, off


class WriteAheadLog:
    """Append-only fsync-able record log at ``path``."""

    def __init__(self, path: str):
        self.path = path
        self._f = None
        self.size = 0            # offset of the last fully-written record
        self._size_known = False
        self._dirty = False  # bytes written since the last fsync

    # -- recovery ----------------------------------------------------------

    def replay(self) -> List[Tuple[str, Any, Any]]:
        """Read and decode the existing log (empty list if absent)."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return []
        records, clean = scan_records(data)
        if clean != len(data):
            logger.warning("gcs wal: truncating torn tail (%d -> %d bytes)",
                           len(data), clean)
            with open(self.path, "r+b") as f:
                f.truncate(clean)
        return records

    # -- appending ---------------------------------------------------------

    def open_append(self):
        if self._f is not None:
            return
        self._f = open(self.path, "ab")
        end = self._f.tell()
        if self._size_known and end > self.size:
            # A previously FAILED append (ENOSPC mid-write) tore the
            # tail; cut back to the last good record so later appends
            # never land after garbage — recovery would discard them.
            logger.warning("gcs wal: truncating torn tail from a failed "
                           "append (%d -> %d bytes)", end, self.size)
            self._f.truncate(self.size)
        else:
            self.size = end
        self._size_known = True

    def append(self, kind: str, key: Any, value: Any) -> int:
        """Append one record; returns bytes written. The write reaches
        the OS immediately (flush); call sync() to force it to disk.
        On failure the file handle is dropped so the next append reopens
        and truncates any torn frame back to the last good record."""
        self.open_append()
        rec = encode_record(kind, key, value)
        try:
            self._f.write(rec)
            self._f.flush()
        except OSError:
            try:
                self._f.close()
            except OSError:
                logger.debug("wal close after failed append failed",
                             exc_info=True)
            self._f = None  # open_append heals the tail next time
            raise
        self.size += len(rec)
        self._dirty = True
        return len(rec)

    def sync(self):
        """fsync pending appends (group commit point)."""
        if self._f is not None and self._dirty:
            os.fsync(self._f.fileno())
            self._dirty = False

    def reset(self):
        """Truncate after a successful compaction (records now live in
        the snapshot)."""
        if self._f is not None:
            self._f.close()
            self._f = None
        with open(self.path, "wb"):
            pass
        self.size = 0
        self._dirty = False

    def close(self):
        if self._f is not None:
            try:
                self.sync()
                self._f.close()
            except OSError:
                logger.debug("wal close failed", exc_info=True)
            self._f = None


def write_snapshot(path: str, blob: bytes):
    """Atomic snapshot write: tmp + fsync + rename — a crash mid-write
    leaves the previous snapshot intact."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_snapshot(path: str) -> Optional[dict]:
    try:
        with open(path, "rb") as f:
            return serialization.loads(f.read())
    except FileNotFoundError:
        return None


class DurableStore:
    """Snapshot + WAL pair rooted at ``path`` (snapshot at ``path``,
    log at ``path + '.wal'``). The GCS folds records back into its
    tables via ``apply``-style replay at recovery; this class only owns
    the bytes."""

    def __init__(self, path: str):
        self.path = path
        self.wal = WriteAheadLog(path + ".wal")

    def recover(self) -> Tuple[Optional[dict], List[Tuple[str, Any, Any]]]:
        """(snapshot dict or None, WAL tail records)."""
        snap = None
        try:
            snap = load_snapshot(self.path)
        except Exception:
            logger.exception("gcs snapshot unreadable; recovering from "
                             "WAL alone")
        records = self.wal.replay()
        return snap, records

    def append(self, kind: str, key: Any, value: Any) -> int:
        return self.wal.append(kind, key, value)

    def compact(self, blob: bytes):
        """Fold: write the full-state snapshot, then truncate the log.
        Must be called with no concurrent appends (the GCS runs this
        synchronously on its event loop). Ordering matters: the rename
        lands the new snapshot (which already contains every WAL
        record's effect) before the log is cut, so a crash between the
        two replays records that are merely redundant, never missing."""
        write_snapshot(self.path, blob)
        self.wal.reset()

    def close(self):
        self.wal.close()
