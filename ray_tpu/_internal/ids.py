"""Binary entity IDs for the runtime.

Design follows the reference's ID scheme (ray src/ray/common/id.h): fixed-size
binary ids with cheap hashing and hex round-tripping. Object ids are
*deterministically* derived from (task id, return index) so that lineage
reconstruction can recompute which task produces a lost object without a
lookup table.

Layout choices (sizes differ from the reference; semantics match):
  JobID             4 bytes, counter assigned by the control plane
  ActorID          16 bytes = 12 random + 4 job
  TaskID           24 bytes = 20 unique + 4 job  (actor creation tasks embed
                    the actor id in the unique part so both are recoverable)
  ObjectID         28 bytes = TaskID + uint32 return-index (big endian)
  NodeID/WorkerID  28 bytes random
  PlacementGroupID 18 bytes = 14 random + 4 job
  ClusterID        28 bytes random
"""

from __future__ import annotations

import os
import random
import struct
import threading

# ID randomness: a per-process PRNG seeded from the OS (os.urandom is a
# syscall per call — measurable at task-submission rates). Collision risk
# is negligible: each process seeds with >=128 bits of OS entropy, and
# forked children reseed so parent/child never share a stream.
_randbytes = random.Random(os.urandom(16)).randbytes


def _reseed_after_fork():
    global _randbytes
    _randbytes = random.Random(os.urandom(16)).randbytes


os.register_at_fork(after_in_child=_reseed_after_fork)

_NIL = b""


class BaseID:
    SIZE = 28
    __slots__ = ("_bytes", "_hash")
    _SALT = 0

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        cls._SALT = hash(cls.__name__)

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary
        # xor with a per-class salt: same cross-type separation as
        # hash((classname, bytes)) without building a tuple per id
        # (ids are constructed twice per task on the hot path)
        self._hash = hash(binary) ^ self._SALT

    @classmethod
    def from_random(cls):
        return cls(_randbytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]}...)"

    def __reduce__(self):
        return (type(self), (self._bytes,))

    @classmethod
    def iter_borrowed(cls, buf):
        """Iterate dict-lookup keys over a packed id array (the done
        stream's contiguous id-bytes buffer) WITHOUT a fresh bytes
        object per id: yields ONE reusable instance re-pointed at each
        SIZE-byte window via a read-only memoryview slice. hash/eq match
        the equivalent bytes-backed id (a read-only memoryview hashes
        like its bytes, and `bytes == memoryview` compares content), so
        dict pops keyed by real ids work.

        The yielded object is BORROWED: valid only until the next
        iteration, for lookups only — never store it (consumers that
        need a retained id use the one already held by the table entry,
        e.g. spec.task_id). `buf` must be bytes (writable buffers are
        unhashable as memoryviews)."""
        size = cls.SIZE
        salt = cls._SALT
        key = cls.__new__(cls)
        mv = memoryview(buf)
        n = len(mv) - (len(mv) % size)
        for off in range(0, n, size):
            window = mv[off:off + size]
            key._bytes = window
            key._hash = hash(window) ^ salt
            yield key


class UniqueID(BaseID):
    SIZE = 28


class NodeID(BaseID):
    SIZE = 28


class WorkerID(BaseID):
    SIZE = 28


class ClusterID(BaseID):
    SIZE = 28


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(struct.pack(">I", value))

    def int_value(self) -> int:
        return struct.unpack(">I", self._bytes)[0]


class ActorID(BaseID):
    SIZE = 16
    UNIQUE_BYTES = 12

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_randbytes(cls.UNIQUE_BYTES) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[self.UNIQUE_BYTES :])


class TaskID(BaseID):
    SIZE = 24
    UNIQUE_BYTES = 20

    @classmethod
    def of(cls, job_id: JobID) -> "TaskID":
        return cls(_randbytes(cls.UNIQUE_BYTES) + job_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        # Embed the actor id so ObjectIDs of the creation task map back to it.
        pad = cls.UNIQUE_BYTES - ActorID.UNIQUE_BYTES
        return cls(
            actor_id.binary()[: ActorID.UNIQUE_BYTES]
            + b"\x00" * pad
            + actor_id.job_id().binary()
        )

    @classmethod
    def for_retry(cls, task_id: "TaskID", attempt: int) -> "TaskID":
        """Deterministic id for the attempt-th retry of a task."""
        base = bytearray(task_id.binary())
        base[0] ^= attempt & 0xFF
        base[1] ^= (attempt >> 8) & 0xFF
        return cls(bytes(base))

    def job_id(self) -> JobID:
        return JobID(self._bytes[self.UNIQUE_BYTES :])


class ObjectID(BaseID):
    SIZE = TaskID.SIZE + 4

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", index))

    @classmethod
    def from_random(cls):
        # `put` objects use a random "task" part with the max index bit set so
        # they can never collide with task returns.
        return cls(_randbytes(TaskID.SIZE) + struct.pack(">I", 0x80000000))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def return_index(self) -> int:
        return struct.unpack(">I", self._bytes[TaskID.SIZE :])[0]

    def is_task_return(self) -> bool:
        return not (self.return_index() & 0x80000000)


class PlacementGroupID(BaseID):
    SIZE = 18
    UNIQUE_BYTES = 14

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(_randbytes(cls.UNIQUE_BYTES) + job_id.binary())


class _Counter:
    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
