"""rtpulint — project-specific static analysis for ray_tpu.

PRs 1-3 established hard invariants (one-lock-per-dep-list refcounting,
no cloudpickle on the per-call task loop, every kill switch registered
in ``config._DEFAULTS``, ``rtpu_*`` metric naming, daemon threads with a
shutdown story). This package machine-checks them on every PR, the way
the reference wires clang-tidy rules and sanitizers into its C++ core:

==== =====================================================================
L001 lock discipline: ``.acquire()`` on a lock outside try/finally;
     blocking calls (time.sleep, RPC ``.call``/``.call_sync``,
     socket/subprocess ops, plasma gets) inside a ``with <lock>:`` body
L002 swallowed exceptions: bare ``except:`` / broad ``except Exception:``
     whose body is only ``pass``/``continue`` with no logging
L003 flag hygiene: every ``CONFIG.<name>`` access and every ``RTPU_*``
     env read must resolve against ``config._DEFAULTS`` (or the
     ``BOOTSTRAP_ENV`` process-plumbing set) — catches typo'd kill
     switches like a misspelled RTPU_NO_FLAT_WIRE
L004 metrics hygiene: Counter/Gauge/Histogram names literal and matching
     ``rtpu_[a-z0-9_]+``, constructed once (module scope / LazyMetrics
     ``_build*`` / ``is None`` guard — never in a loop), and one
     consistent label set per series name across the whole tree
L005 thread hygiene: ``threading.Thread(daemon=True)`` must be created
     via ``threads.spawn_daemon`` or registered with
     ``threads.register_daemon_thread`` in the same scope
L006 hot-path pickle: serialization/cloudpickle/pickle ``dumps``/``loads``
     in the hot-path modules (rpc.py, task_spec.py, core_worker.py) must
     sit behind the flat-wire fallback gate (allowlisted with a
     justification, one entry per call site scope)
L007 loop/shard hygiene: ``asyncio.get_event_loop()`` is banned in
     ``_internal/`` (ambient-loop is wrong once owner shards put >1
     loop in the process — use ``get_running_loop()`` or an explicit
     handle); and every cross-object read of a ``# shard-local``
     registered table (the loop-confined owner-shard dicts) must carry
     a ``# cross-shard ok: <why>`` justification on the same line
L008 logging hygiene: bare ``print()`` in ``_internal/`` (outside
     ``__main__`` entrypoints) bypasses the log plane's attribution
     and ring capture — use the structured logger or annotate the line
     ``# stdout ok: <why>``; ``logging.getLogger`` must take
     ``__name__`` (or no arg for root), and the module-level handle is
     named ``logger``
L009 retry backoff: ``time.sleep``/``asyncio.sleep`` on the error path
     of a loop in ``_internal/`` is a hand-rolled retry schedule — use
     ``backoff.Backoff`` (jittered exponential, cap, deadline) so
     fleet-wide retry storms don't synchronize, or annotate the line
     ``# backoff ok: <why>``
L010 metric-catalog sync: every ``rtpu_*`` series constructed in the
     tree must have a row in README.md's metric catalog table, and
     every cataloged series must still be constructed somewhere —
     both directions, so the catalog can't silently rot
==== =====================================================================

On top of the per-file L-series, ``.crossmod`` runs a two-pass
cross-module analysis (pass 1 indexes the whole tree: defs, internal
call edges, async defs, jit-wrapped functions; pass 2 runs flow-aware
rules over the index):

==== =====================================================================
A001 fire-and-forget ``create_task``/``ensure_future``: handle dropped
     and the coroutine (call graph walked through thin await-wrappers)
     has no terminal exception sink — use ``_internal.aio.spawn()``,
     retain the handle, or annotate ``# task ok: <why>``
A002 coroutine called as a bare statement but never awaited/scheduled
     (the body never runs)
A003 known-blocking call (the L001 table) lexically inside an
     ``async def`` — stalls the whole loop; ``run_in_executor`` it or
     annotate ``# blocking ok: <why>``
J001 host-sync primitive (``block_until_ready``, ``device_get``,
     ``np.asarray``, ``.item()``, ``float()/int()`` of an array)
     reachable from a per-step hot function (jit-wrapped, driving a
     jit step, or annotated ``# rtpu: hot-loop``) — annotate deliberate
     sync points ``# host-sync ok: <why>``
J002 jit-staged function closing over a mutable dict/list (module
     global or enclosing-function local): stale captures / recompile
     hazard — pass as argument or annotate ``# jit capture ok: <why>``
J003 donated-argument reuse after a ``donate_argnums`` call site —
     rebind the result or annotate ``# donate ok: <why>``
==== =====================================================================

Violations report ``file:line`` and carry a stable allowlist key
``RULE path:scope`` (scope = enclosing def/class qualname, so the key
survives unrelated line shifts). ``allowlist.txt`` is a burn-down list:
tests assert it only shrinks and that every entry still matches a live
violation (stale entries are themselves errors).

Run: ``python -m ray_tpu._internal.lint [--json] [--changed]`` or
``cli lint``. Exit codes: 0 clean, 1 violations (or stale/malformed
allowlist entries), 2 usage/environment error (bad --root, git
unavailable for --changed). The companion *dynamic* checkers live in
``.sanitizer`` (lock-order) and ``.loopstall`` (event-loop stall
budget); both arm under ``RTPU_SANITIZE=1``.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import crossmod
from .rules import (MetricDecl, ShardAccess, ShardTableDecl, Violation,
                    check_shard_confinement, lint_source)

__all__ = [
    "Violation", "LintReport", "lint_source", "run_lint",
    "load_allowlist", "default_allowlist_path", "package_root", "main",
]

_SKIP_DIRS = {"__pycache__", "generated", "protos"}
_SKIP_SUFFIXES = (os.path.join("dashboard", "client"),)  # JS assets


def package_root() -> str:
    """Directory containing the ``ray_tpu`` package (the lint root)."""
    here = os.path.dirname(os.path.abspath(__file__))  # .../ray_tpu/_internal/lint
    return os.path.dirname(os.path.dirname(os.path.dirname(here)))


def default_allowlist_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "allowlist.txt")


@dataclass
class AllowEntry:
    key: str            # "L002 ray_tpu/_internal/gcs.py:GcsServer._sweep"
    justification: str
    lineno: int
    used: int = 0


@dataclass
class LintReport:
    violations: List[Violation] = field(default_factory=list)
    allowlisted: List[Violation] = field(default_factory=list)
    unused_allowlist: List[AllowEntry] = field(default_factory=list)
    bad_allowlist_lines: List[str] = field(default_factory=list)
    checked_files: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations and not self.unused_allowlist \
            and not self.bad_allowlist_lines

    def to_json(self) -> str:
        return json.dumps({
            "ok": self.ok,
            "checked_files": self.checked_files,
            "violations": [v.to_dict() for v in self.violations],
            "allowlisted": len(self.allowlisted),
            "unused_allowlist": [e.key for e in self.unused_allowlist],
            "bad_allowlist_lines": self.bad_allowlist_lines,
        }, indent=1)

    def render(self) -> str:
        lines = []
        for v in sorted(self.violations, key=lambda v: (v.path, v.line)):
            lines.append(f"{v.path}:{v.line}: {v.rule} {v.message}")
            lines.append(f"    allowlist key: {v.key}")
        for e in self.unused_allowlist:
            lines.append(f"allowlist.txt:{e.lineno}: unused entry "
                         f"(fixed? delete it): {e.key}")
        for bad in self.bad_allowlist_lines:
            lines.append(f"allowlist.txt: malformed line: {bad}")
        lines.append(f"{len(self.violations)} violation(s), "
                     f"{len(self.allowlisted)} allowlisted, "
                     f"{self.checked_files} files checked")
        return "\n".join(lines)


def load_allowlist(path: str) -> Tuple[List[AllowEntry], List[str]]:
    """Parse allowlist.txt: ``RULE path:scope -- justification`` per line
    (``#`` comments and blank lines ignored). A justification is
    mandatory — an unexplained suppression is itself a violation."""
    entries: List[AllowEntry] = []
    bad: List[str] = []
    if not os.path.exists(path):
        return entries, bad
    with open(path, encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(None, 2)
            if len(parts) < 3 or not re.match(r"^[ALJ]\d{3}$", parts[0]) \
                    or ":" not in parts[1]:
                bad.append(line)
                continue
            rule, loc, just = parts
            just = just.lstrip("-— ").strip()
            if not just:
                bad.append(line)
                continue
            entries.append(AllowEntry(key=f"{rule} {loc}",
                                      justification=just, lineno=lineno))
    return entries, bad


def iter_source_files(root: str):
    pkg = os.path.join(root, "ray_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(
            d for d in dirnames if d not in _SKIP_DIRS
            and not os.path.join(dirpath, d).endswith(_SKIP_SUFFIXES))
        for name in sorted(filenames):
            if name.endswith(".py") and not name.endswith("_pb2.py"):
                yield os.path.join(dirpath, name)


def run_lint(root: Optional[str] = None,
             allowlist_path: Optional[str] = None,
             use_allowlist: bool = True) -> LintReport:
    """Lint every source file under ``<root>/ray_tpu``."""
    root = root or package_root()
    report = LintReport()
    entries: List[AllowEntry] = []
    if use_allowlist:
        path = allowlist_path or default_allowlist_path()
        entries, report.bad_allowlist_lines = load_allowlist(path)
    by_key: Dict[str, AllowEntry] = {e.key: e for e in entries}

    all_violations: List[Violation] = []
    metric_decls: List[MetricDecl] = []
    shard_decls: List[ShardTableDecl] = []
    shard_accesses: List[ShardAccess] = []
    module_facts: List[crossmod.ModuleFacts] = []
    for filepath in iter_source_files(root):
        rel = os.path.relpath(filepath, root)
        try:
            with open(filepath, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            all_violations.append(Violation(
                rule="L000", path=rel, line=0, scope="<module>",
                message=f"unreadable source file: {e}"))
            continue
        # One parse feeds both the per-file visitor and the
        # cross-module facts collector.
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            all_violations.append(Violation(
                rule="L000", path=rel, line=e.lineno or 0,
                scope="<module>", message=f"syntax error: {e.msg}"))
            report.checked_files += 1
            continue
        violations, decls, sdecls, saccs = lint_source(src, rel, tree=tree)
        all_violations.extend(violations)
        metric_decls.extend(decls)
        shard_decls.extend(sdecls)
        shard_accesses.extend(saccs)
        module_facts.append(crossmod.collect(tree, rel, src.splitlines()))
        report.checked_files += 1

    all_violations.extend(_check_metric_consistency(metric_decls))
    all_violations.extend(_check_metric_catalog(metric_decls, root))
    all_violations.extend(
        check_shard_confinement(shard_decls, shard_accesses))
    all_violations.extend(crossmod.check_tree(module_facts))

    for v in all_violations:
        entry = by_key.get(v.key)
        if entry is not None:
            entry.used += 1
            report.allowlisted.append(v)
        else:
            report.violations.append(v)
    report.unused_allowlist = [e for e in entries if not e.used]
    return report


def _check_metric_consistency(decls: List[MetricDecl]) -> List[Violation]:
    """L004 cross-file check: one label set (and kind) per series name.
    Two declarations of the same series with different tag_keys merge
    into invalid exposition (duplicate/contradictory sample lines)."""
    first: Dict[str, MetricDecl] = {}
    out: List[Violation] = []
    for d in decls:
        prev = first.setdefault(d.name, d)
        if prev is d:
            continue
        if d.tag_keys != prev.tag_keys:
            out.append(Violation(
                rule="L004", path=d.path, line=d.line, scope=d.scope,
                message=(f"metric {d.name!r} declared with labels "
                         f"{list(d.tag_keys)} but "
                         f"{prev.path}:{prev.line} declared "
                         f"{list(prev.tag_keys)} — one label set per "
                         "series")))
        elif d.kind != prev.kind:
            out.append(Violation(
                rule="L004", path=d.path, line=d.line, scope=d.scope,
                message=(f"metric {d.name!r} declared as {d.kind} but "
                         f"{prev.path}:{prev.line} declared "
                         f"{prev.kind}")))
    return out


def _catalog_names(root: str) -> Tuple[Dict[str, int], Optional[str]]:
    """Parse README.md's metric-catalog table: every backticked
    ``rtpu_*`` token in the *first* cell of a table row is a cataloged
    series name. Returns ``{name: lineno}`` (first occurrence wins) and
    the README's path, or ``(_, None)`` when no README exists (sdist
    slices of the tree skip the check rather than flag everything)."""
    import re
    readme = os.path.join(root, "README.md")
    if not os.path.exists(readme):
        return {}, None
    names: Dict[str, int] = {}
    with open(readme, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            if not line.lstrip().startswith("|"):
                continue
            cells = line.split("|")
            if len(cells) < 3:
                continue
            for tok in re.findall(r"`(rtpu_[a-z0-9_]+)`", cells[1]):
                names.setdefault(tok, lineno)
    return names, readme


def _check_metric_catalog(decls: List[MetricDecl],
                          root: str) -> List[Violation]:
    """L010 cross-file check: the README metric catalog and the set of
    constructed series must match in both directions. An uncataloged
    series is invisible to operators reading the docs; a cataloged
    series nobody constructs is a dashboard query that silently returns
    nothing."""
    catalog, readme = _catalog_names(root)
    if readme is None:
        return []
    out: List[Violation] = []
    first: Dict[str, MetricDecl] = {}
    for d in decls:
        first.setdefault(d.name, d)
    for name in sorted(first):
        if name not in catalog:
            d = first[name]
            out.append(Violation(
                rule="L010", path=d.path, line=d.line, scope=d.scope,
                message=(f"metric {name!r} constructed here but missing "
                         "from README.md's metric catalog — add a row")))
    for name in sorted(catalog):
        if name not in first:
            out.append(Violation(
                rule="L010", path="README.md", line=catalog[name],
                scope=name,
                message=(f"cataloged metric {name!r} is not constructed "
                         "anywhere in the tree — stale row")))
    return out


def changed_files(root: str) -> List[str]:
    """Repo-relative paths touched vs HEAD (staged + unstaged +
    untracked), for ``--changed``. Raises OSError/CalledProcessError
    when git is unavailable — main() maps that to exit code 2."""
    import subprocess
    rels: List[str] = []
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        out = subprocess.check_output(cmd, cwd=root, text=True,
                                      stderr=subprocess.DEVNULL)
        rels.extend(line.strip() for line in out.splitlines()
                    if line.strip())
    return sorted(set(rels))


def main(argv: Optional[List[str]] = None) -> int:
    """Exit codes: 0 clean; 1 violations (or stale/malformed allowlist
    entries); 2 usage or environment error (``--changed`` without a
    usable git checkout)."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="rtpulint",
        description="ray_tpu project lint (rules L001-L010, A001-A003, "
                    "J001-J003)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--root", default=None,
                        help="directory containing the ray_tpu package")
    parser.add_argument("--allowlist", default=None,
                        help="alternative allowlist file")
    parser.add_argument("--no-allowlist", action="store_true",
                        help="report allowlisted violations too")
    parser.add_argument("--changed", action="store_true",
                        help="report only violations in files changed "
                             "vs HEAD (the whole tree is still "
                             "analyzed: cross-module rules need the "
                             "full index)")
    args = parser.parse_args(argv)
    report = run_lint(root=args.root, allowlist_path=args.allowlist,
                      use_allowlist=not args.no_allowlist)
    if args.changed:
        root = args.root or package_root()
        try:
            touched = set(changed_files(root))
        except Exception as e:  # noqa: BLE001 — any git failure is fatal
            print(f"rtpulint: --changed needs git: {e}",  # stdout ok: CLI
                  file=sys.stderr)
            return 2
        report.violations = [v for v in report.violations
                             if v.path in touched]
        # Allowlist staleness stays a whole-tree property: an entry
        # whose violation lives in an untouched file is still live.
    print(report.to_json() if args.json  # stdout ok: CLI output
          else report.render())
    return 0 if report.ok else 1
