"""``python -m ray_tpu._internal.lint [--json]`` — run rtpulint."""

import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
