"""Cross-module pass for rtpulint: asyncio lifecycle + JAX hygiene.

The per-file visitor in :mod:`.rules` is deliberately blind across
module boundaries; this module adds the two-pass engine that isn't.
Pass 1 (:func:`collect`) walks every file's AST once and records
*facts* — function definitions (async? jit-wrapped? has an exception
sink?), internal call edges, task-spawn sites, host-sync primitives,
mutable module globals, ``donate_argnums`` wrappers. Pass 2
(:func:`check_tree`) folds the whole-tree index and runs the flow-aware
rules:

==== =====================================================================
A001 fire-and-forget ``create_task``/``ensure_future`` whose handle is
     dropped AND whose coroutine has no terminal exception sink (a broad
     ``except`` that doesn't just re-raise, found by walking the local
     call graph through thin ``await``-delegation wrappers). An
     unhandled exception in such a task is invisible until the loop's
     exception handler prints it at shutdown — use ``_internal.aio
     .spawn()`` (logs + counts failures), retain the handle, or
     annotate ``# task ok: <why>``
A002 coroutine called as a bare statement but never awaited or
     scheduled — the call builds a coroutine object and drops it; the
     body never runs (Python warns only at GC time, and only sometimes)
A003 known-blocking call (the L001 blocking table: ``time.sleep``,
     subprocess, socket connect, ``.call_sync``/``.run_sync``,
     socket send/recv) lexically inside an ``async def`` — it stalls
     the whole event loop, not just this coroutine; move it to
     ``run_in_executor`` or annotate ``# blocking ok: <why>``
J001 host-sync primitive (``.block_until_ready()``, ``device_get``,
     ``np.asarray``/``np.array``, ``.item()``, ``float()``/``int()`` of
     an array) reachable from a per-step hot function — jit-wrapped,
     annotated ``# rtpu: hot-loop``, or directly driving a jit-wrapped
     step. Every such sync serializes host and device (the Podracer
     failure mode); deliberate sync points annotate
     ``# host-sync ok: <why>``
J002 jit-staged function closes over a mutable dict/list (module
     global or enclosing-function local): mutations after trace are
     silently stale (captured as constants) or force recompiles —
     pass it as an argument, or annotate ``# jit capture ok: <why>``
J003 donated-argument reuse: after ``f = jax.jit(g, donate_argnums=k)``
     the buffer passed at position ``k`` is invalidated by the call;
     a later read of the same variable (without rebinding) is
     use-after-donate. Rebind (``state = step(state)``) or annotate
     ``# donate ok: <why>``
==== =====================================================================

All six report the same stable allowlist key shape as the L-series
(``RULE path:scope``). The sibling *dynamic* checker for the A-series
bug class is :mod:`.loopstall` (event-loop stall sanitizer).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .rules import Violation, _broad_handler, _dotted, _terminal

__all__ = ["ModuleFacts", "TreeIndex", "collect", "collect_source",
           "check_tree", "analyze_sources"]

# -- suppression marks (same-line comments) ---------------------------------
_TASK_OK_MARK = "# task ok"
_BLOCKING_OK_MARK = "# blocking ok"
_HOST_SYNC_OK_MARK = "# host-sync ok"
_JIT_CAPTURE_OK_MARK = "# jit capture ok"
_DONATE_OK_MARK = "# donate ok"
_HOT_LOOP_MARK = "# rtpu: hot-loop"

_SPAWN_TERMS = {"create_task", "ensure_future"}

# A003 reuses the L001 blocking tables, minus the bare ``.call`` method:
# in this codebase ``.call()`` is the *async* RPC verb (``.call_sync``
# is its blocking twin), so flagging it inside async defs would ban the
# normal path.
_A003_DOTTED = {
    "time.sleep",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "socket.create_connection",
}
_A003_METHODS = {"call_sync", "run_sync", "recv", "sendall", "accept"}

# J001 host-sync primitives.
_HOST_SYNC_DOTTED = {
    "jax.device_get", "np.asarray", "numpy.asarray", "onp.asarray",
    "np.array", "numpy.array", "onp.array",
}
_JIT_DOTTED = {"jax.jit", "jit"}
_PARTIAL_DOTTED = {"partial", "functools.partial"}
_MUTABLE_CTORS = {"dict", "list", "defaultdict", "OrderedDict"}

_MAX_SINK_DEPTH = 5      # A001 delegation walk
_J001_DEPTH = 2          # J001 reachability from a hot function

# J001: int()/float() over shape/size metadata is host math on ints the
# runtime already has — never a device sync. Exempt args whose subtree
# reads one of these attributes or calls one of these size functions.
_SHAPE_ATTRS = {"shape", "size", "ndim", "nbytes", "itemsize"}
_SHAPE_FUNCS = {"len", "prod", "size", "ndim"}


def _is_shape_math(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _SHAPE_ATTRS:
            return True
        if isinstance(node, ast.Call) \
                and _terminal(_dotted(node.func)) in _SHAPE_FUNCS:
            return True
    return False


# ---------------------------------------------------------------------------
# pass-1 fact records
# ---------------------------------------------------------------------------


@dataclass
class FuncInfo:
    module: str
    qualname: str            # scope-style qualname ("Cls.meth")
    name: str                # bare name
    line: int
    is_async: bool = False
    jit: bool = False        # jit-decorated, or jax.jit-wrapped by name
    hot_annotated: bool = False   # "# rtpu: hot-loop" on the def line
    has_sink: bool = False   # broad except that doesn't just re-raise
    delegate_only: bool = False   # body is nothing but awaited calls
    delegates: Tuple[str, ...] = ()   # terminal names it awaits
    # terminal callee name -> called inside a loop? (True wins)
    calls: Dict[str, bool] = field(default_factory=dict)
    parent: Optional["FuncInfo"] = None            # enclosing function
    # (kind, line, annotated, in_loop) host-sync sites in this func
    host_syncs: List[Tuple[str, int, bool, bool]] = field(
        default_factory=list)
    # Name loads/stores: {name: [lineno, ...]} — J003's reuse window
    loads: Dict[str, List[int]] = field(default_factory=dict)
    stores: Dict[str, List[int]] = field(default_factory=dict)
    local_names: Set[str] = field(default_factory=set)
    # locals bound to a dict/list literal (J002 closure hazard)
    mutable_locals: Dict[str, int] = field(default_factory=dict)
    # free Name loads (resolved against globals in pass 2): (name, line,
    # annotated)
    free_loads: List[Tuple[str, int, bool]] = field(default_factory=list)


@dataclass
class SpawnSite:
    """A create_task/ensure_future call whose handle is dropped."""
    module: str
    line: int
    scope: str
    coro_term: Optional[str]     # terminal name of the coroutine call
    coro_recv: Optional[str]     # dotted receiver ("self.gcs"), if any
    annotated: bool


@dataclass
class StmtCall:
    """A bare expression-statement call (A002 candidate)."""
    module: str
    line: int
    scope: str
    term: str
    recv: Optional[str]          # dotted receiver, None for bare names


@dataclass
class DonationCall:
    """A call through a donate_argnums wrapper with a plain-Name arg at
    a donated position."""
    module: str
    line: int
    scope: str
    callee: str
    argname: str
    annotated: bool
    func: FuncInfo               # enclosing function (loads/stores live here)


@dataclass
class ModuleFacts:
    path: str
    funcs: List[FuncInfo] = field(default_factory=list)
    by_name: Dict[str, List[FuncInfo]] = field(default_factory=dict)
    # from-import bindings: local name -> (module path guess, orig name)
    imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    spawns: List[SpawnSite] = field(default_factory=list)
    stmt_calls: List[StmtCall] = field(default_factory=list)
    blocking_in_async: List[Violation] = field(default_factory=list)
    mutable_globals: Dict[str, int] = field(default_factory=dict)
    # callable name -> donated arg positions (jax.jit donate_argnums)
    donations: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    donation_calls: List[DonationCall] = field(default_factory=list)


# ---------------------------------------------------------------------------
# pass-1 visitor
# ---------------------------------------------------------------------------


def _resolve_import(path: str, level: int, module: str) -> Optional[str]:
    """Guess the repo-relative .py path a from-import refers to.
    ``path`` is the importing file ("ray_tpu/serve/_private/proxy.py")."""
    if level == 0:
        if not module.startswith("ray_tpu"):
            return None
        return module.replace(".", "/") + ".py"
    parts = path.split("/")[:-1]          # package dirs of the importer
    if level - 1 > 0:
        parts = parts[:-(level - 1)] if level - 1 <= len(parts) else []
    if module:
        parts = parts + module.split(".")
    return "/".join(parts) + ".py" if parts else None


class _FactsVisitor(ast.NodeVisitor):
    def __init__(self, path: str, src_lines: Sequence[str]):
        self.facts = ModuleFacts(path=path)
        self._lines = src_lines
        # Pseudo-function holding module-level (and class-body) code so
        # J003 works in train-script-style modules.
        self._module_func = FuncInfo(module=path, qualname="<module>",
                                     name="<module>", line=0)
        self.facts.funcs.append(self._module_func)
        self._func_stack: List[FuncInfo] = [self._module_func]
        self._scope_names: List[str] = []
        self._class_depth = 0
        self._loop_depth = 0      # For/While nesting INSIDE current func
        self._awaited: Set[int] = set()     # id() of awaited Call nodes
        self._dropped: Set[int] = set()     # id() of discarded-value Calls
        # jax.jit(f) wrappers seen: (bare name of f, enclosing func) —
        # resolved after the walk so forward references work
        self._jit_wraps: List[Tuple[str, FuncInfo]] = []

    # -- helpers ------------------------------------------------------------

    @property
    def _fn(self) -> FuncInfo:
        return self._func_stack[-1]

    @property
    def scope(self) -> str:
        return ".".join(self._scope_names) if self._scope_names \
            else "<module>"

    def _marked(self, node: ast.AST, mark: str) -> bool:
        line = getattr(node, "lineno", 0)
        if 0 < line <= len(self._lines):
            return mark in self._lines[line - 1]
        return False

    # -- imports ------------------------------------------------------------

    def visit_ImportFrom(self, node: ast.ImportFrom):
        target = _resolve_import(self.facts.path, node.level,
                                 node.module or "")
        if target is not None:
            for alias in node.names:
                if alias.name == "*":
                    continue
                self.facts.imports[alias.asname or alias.name] = \
                    (target, alias.name)
        for alias in node.names:
            self._fn.local_names.add(alias.asname
                                     or alias.name.split(".")[0])
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            self._fn.local_names.add(alias.asname
                                     or alias.name.split(".")[0])
        self.generic_visit(node)

    # -- scopes -------------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef):
        self._scope_names.append(node.name)
        self._class_depth += 1
        self.generic_visit(node)
        self._class_depth -= 1
        self._scope_names.pop()

    def _visit_func(self, node, is_async: bool):
        info = FuncInfo(
            module=self.facts.path,
            qualname=".".join(self._scope_names + [node.name]),
            name=node.name, line=node.lineno, is_async=is_async,
            parent=(self._fn if self._fn is not self._module_func
                    else None))
        info.hot_annotated = self._marked(node, _HOT_LOOP_MARK)
        info.jit = self._decorated_jit(node)
        for arg in (list(node.args.posonlyargs) + list(node.args.args)
                    + list(node.args.kwonlyargs)
                    + [a for a in (node.args.vararg, node.args.kwarg)
                       if a is not None]):
            info.local_names.add(arg.arg)
        self._collect_delegation(node, info)
        self.facts.funcs.append(info)
        self.facts.by_name.setdefault(node.name, []).append(info)
        self._func_stack.append(info)
        self._scope_names.append(node.name)
        outer_loop, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = outer_loop
        self._scope_names.pop()
        self._func_stack.pop()

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _visit_loop

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._visit_func(node, is_async=False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._visit_func(node, is_async=True)

    def visit_Lambda(self, node: ast.Lambda):
        # A lambda body doesn't run where it's written: give it its own
        # (non-async) scope so A003 doesn't flag executor thunks, and
        # mark a spawn that IS the whole body as dropped (the common
        # `call_soon(lambda: ensure_future(coro()))` trampoline returns
        # the task to a caller that discards it).
        if isinstance(node.body, ast.Call):
            self._dropped.add(id(node.body))
        info = FuncInfo(module=self.facts.path,
                        qualname=".".join(self._scope_names + ["<lambda>"]),
                        name="<lambda>", line=node.lineno,
                        parent=(self._fn if self._fn is not self._module_func
                                else None))
        for arg in node.args.args:
            info.local_names.add(arg.arg)
        self.facts.funcs.append(info)
        self._func_stack.append(info)
        self._scope_names.append("<lambda>")
        outer_loop, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = outer_loop
        self._scope_names.pop()
        self._func_stack.pop()

    def _decorated_jit(self, node) -> bool:
        for dec in node.decorator_list:
            d = _dotted(dec)
            if d in _JIT_DOTTED:
                return True
            if isinstance(dec, ast.Call):
                dfunc = _dotted(dec.func)
                if dfunc in _JIT_DOTTED:
                    self._record_donation(node.name, dec)
                    return True
                if dfunc in _PARTIAL_DOTTED and dec.args \
                        and _dotted(dec.args[0]) in _JIT_DOTTED:
                    self._record_donation(node.name, dec)
                    return True
        return False

    def _record_donation(self, callee_name: str, call: ast.Call):
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            positions: List[int] = []
            vals = kw.value.elts if isinstance(kw.value, (ast.Tuple,
                                                          ast.List)) \
                else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    positions.append(v.value)
            if positions:
                self.facts.donations[callee_name] = tuple(sorted(positions))

    def _collect_delegation(self, node, info: FuncInfo):
        """Thin-wrapper detection for the A001 sink walk: a body that is
        nothing but ``await <call>`` statements (plus a docstring)
        delegates its exception story to the awaited callees."""
        body = list(node.body)
        if body and isinstance(body[0], ast.Expr) \
                and isinstance(body[0].value, ast.Constant) \
                and isinstance(body[0].value.value, str):
            body = body[1:]
        terms: List[str] = []
        for stmt in body:
            value = None
            if isinstance(stmt, (ast.Expr, ast.Return)):
                value = stmt.value
            elif isinstance(stmt, ast.Pass):
                continue
            if isinstance(value, ast.Await) \
                    and isinstance(value.value, ast.Call):
                term = _terminal(_dotted(value.value.func))
                if term:
                    terms.append(term)
                    continue
            return  # anything else: not a pure delegation wrapper
        if terms:
            info.delegate_only = True
            info.delegates = tuple(terms)

    # -- exception sinks (A001) ---------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if _broad_handler(node) is not None and not all(
                isinstance(s, ast.Raise) for s in node.body):
            self._fn.has_sink = True
        self.generic_visit(node)

    # -- statement / await context ------------------------------------------

    def visit_Expr(self, node: ast.Expr):
        if isinstance(node.value, ast.Call):
            self._dropped.add(id(node.value))
            call = node.value
            term = _terminal(_dotted(call.func))
            # A002 candidate: a bare statement call that isn't awaited
            # and isn't itself a spawn. Recorded here (statement
            # context); resolution to an async def happens in pass 2.
            if term and term not in _SPAWN_TERMS:
                recv = None
                if isinstance(call.func, ast.Attribute):
                    recv = _dotted(call.func.value)
                self.facts.stmt_calls.append(StmtCall(
                    module=self.facts.path, line=call.lineno,
                    scope=self.scope, term=term, recv=recv))
        self.generic_visit(node)

    def visit_Await(self, node: ast.Await):
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
        self.generic_visit(node)

    # -- names (J002/J003) --------------------------------------------------

    def visit_Name(self, node: ast.Name):
        fn = self._fn
        if isinstance(node.ctx, ast.Load):
            fn.loads.setdefault(node.id, []).append(node.lineno)
            if node.id not in fn.local_names:
                fn.free_loads.append(
                    (node.id, node.lineno,
                     self._marked(node, _JIT_CAPTURE_OK_MARK)))
        else:
            fn.stores.setdefault(node.id, []).append(node.lineno)
            fn.local_names.add(node.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        self._maybe_mutable_binding(node.targets, node.value, node.lineno)
        self._maybe_jit_wrap(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._maybe_mutable_binding([node.target], node.value,
                                        node.lineno)
            self._maybe_jit_wrap([node.target], node.value)
        self.generic_visit(node)

    def _maybe_mutable_binding(self, targets, value, lineno: int):
        mutable = isinstance(value, (ast.Dict, ast.List, ast.DictComp,
                                     ast.ListComp)) \
            or (isinstance(value, ast.Call)
                and _terminal(_dotted(value.func)) in _MUTABLE_CTORS)
        if not mutable:
            return
        for target in targets:
            if not isinstance(target, ast.Name):
                continue
            if self._fn is self._module_func and self._class_depth == 0:
                self.facts.mutable_globals.setdefault(target.id, lineno)
            elif self._fn is not self._module_func:
                self._fn.mutable_locals.setdefault(target.id, lineno)

    def _maybe_jit_wrap(self, targets, value):
        """``step = jax.jit(f, donate_argnums=...)``: mark ``f`` as
        jit-staged and register the wrapper name's donated positions."""
        if not (isinstance(value, ast.Call)
                and _dotted(value.func) in _JIT_DOTTED and value.args):
            return
        wrapped = value.args[0]
        if isinstance(wrapped, ast.Name):
            self._jit_wraps.append((wrapped.id, self._fn))
        for target in targets:
            name = None
            if isinstance(target, ast.Name):
                name = target.id
            elif isinstance(target, ast.Attribute):
                name = target.attr
            if name:
                self._record_donation(name, value)

    # -- calls --------------------------------------------------------------

    def visit_Call(self, node: ast.Call):
        dotted = _dotted(node.func)
        term = _terminal(dotted)
        fn = self._fn
        in_loop = self._loop_depth > 0
        # Call-graph edges (J001 reachability) only for calls that can
        # resolve to tree-internal defs: bare names and self/cls
        # methods. `tx.update(...)` must not edge to OUR `update`.
        if term and (isinstance(node.func, ast.Name)
                     or (isinstance(node.func, ast.Attribute)
                         and isinstance(node.func.value, ast.Name)
                         and node.func.value.id in ("self", "cls"))):
            fn.calls[term] = fn.calls.get(term, False) or in_loop

        # A001: spawn with a discarded handle
        if term in _SPAWN_TERMS and id(node) in self._dropped:
            coro_term = coro_recv = None
            if node.args and isinstance(node.args[0], ast.Call):
                coro_term = _terminal(_dotted(node.args[0].func)) or None
                if isinstance(node.args[0].func, ast.Attribute):
                    coro_recv = _dotted(node.args[0].func.value)
            self.facts.spawns.append(SpawnSite(
                module=self.facts.path, line=node.lineno, scope=self.scope,
                coro_term=coro_term, coro_recv=coro_recv,
                annotated=self._marked(node, _TASK_OK_MARK)))

        # A003: blocking call lexically inside an async def
        if fn.is_async and id(node) not in self._awaited \
                and (dotted in _A003_DOTTED or term in _A003_METHODS) \
                and not self._marked(node, _BLOCKING_OK_MARK):
            self.facts.blocking_in_async.append(Violation(
                rule="A003", path=self.facts.path, line=node.lineno,
                scope=self.scope,
                message=(f"blocking call {dotted or term}() inside "
                         f"async def {fn.name} stalls the whole event "
                         "loop — run_in_executor it, use the async "
                         "variant, or annotate `# blocking ok: <why>`")))

        # J001: host-sync primitive sites
        sync = None
        if term == "block_until_ready" \
                and isinstance(node.func, ast.Attribute):
            sync = ".block_until_ready()"
        elif dotted in _HOST_SYNC_DOTTED or term == "device_get":
            sync = f"{dotted or term}()"
        elif term == "item" and isinstance(node.func, ast.Attribute) \
                and not node.args and not node.keywords:
            sync = ".item()"
        elif isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int") \
                and len(node.args) == 1 \
                and isinstance(node.args[0], (ast.Call, ast.Attribute,
                                              ast.Subscript, ast.Name)) \
                and not _is_shape_math(node.args[0]):
            sync = f"{node.func.id}(...)"
        if sync is not None:
            fn.host_syncs.append(
                (sync, node.lineno,
                 self._marked(node, _HOST_SYNC_OK_MARK), in_loop))

        # J003: call through a donate_argnums wrapper
        if term in self.facts.donations:
            positions = self.facts.donations[term]
            for pos in positions:
                if pos < len(node.args) \
                        and isinstance(node.args[pos], ast.Name):
                    self.facts.donation_calls.append(DonationCall(
                        module=self.facts.path, line=node.lineno,
                        scope=self.scope, callee=term,
                        argname=node.args[pos].id,
                        annotated=self._marked(node, _DONATE_OK_MARK),
                        func=fn))

        self.generic_visit(node)

    # -- entry --------------------------------------------------------------

    def run(self, tree: ast.Module) -> ModuleFacts:
        self.visit(tree)
        for name, enclosing in self._jit_wraps:
            # Python name resolution, approximately: a wrap written
            # inside a function binds to that function's local defs
            # first; otherwise fall back to top-level defs (so
            # `self._step = jax.jit(update)` inside __init__ marks the
            # nested `update`, NOT an unrelated method of that name).
            cands = self.facts.by_name.get(name, ())
            local = [c for c in cands
                     if c.parent is enclosing
                     and enclosing is not self._module_func]
            targets = local or [c for c in cands if c.parent is None] \
                or list(cands)
            for info in targets:
                info.jit = True
        return self.facts


def collect(tree: ast.Module, path: str,
            src_lines: Sequence[str]) -> ModuleFacts:
    """Pass 1 over one parsed module."""
    return _FactsVisitor(path, src_lines).run(tree)


def collect_source(src: str, path: str) -> Optional[ModuleFacts]:
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None   # rules.lint_source already reports L000
    return collect(tree, path, src.splitlines())


# ---------------------------------------------------------------------------
# pass 2: the whole-tree index + checks
# ---------------------------------------------------------------------------


class TreeIndex:
    def __init__(self, modules: List[ModuleFacts]):
        self.modules: Dict[str, ModuleFacts] = {m.path: m for m in modules}
        self.all_by_name: Dict[str, List[FuncInfo]] = {}
        for m in modules:
            for name, infos in m.by_name.items():
                self.all_by_name.setdefault(name, []).extend(infos)

    def resolve(self, module: str, name: str,
                tree_wide: bool = True) -> List[FuncInfo]:
        """Candidate defs for a bare callee name seen in ``module``:
        same-module first, then the from-import edge, then (optionally)
        every def of that name anywhere in the tree."""
        facts = self.modules.get(module)
        if facts is not None:
            local = facts.by_name.get(name)
            if local:
                return local
            imp = facts.imports.get(name)
            if imp is not None:
                target, orig = imp
                tm = self.modules.get(target) \
                    or self.modules.get(target[:-3] + "/__init__.py")
                if tm is not None and tm.by_name.get(orig):
                    return tm.by_name[orig]
        if tree_wide:
            return self.all_by_name.get(name, [])
        return []

    # -- A001 sink walk -----------------------------------------------------

    def has_sink(self, info: FuncInfo, _depth: int = 0,
                 _seen: Optional[Set[int]] = None) -> bool:
        if info.has_sink:
            return True
        if _depth >= _MAX_SINK_DEPTH or not info.delegate_only:
            return False
        seen = _seen if _seen is not None else set()
        if id(info) in seen:
            return False
        seen.add(id(info))
        for term in info.delegates:
            cands = self.resolve(info.module, term, tree_wide=False)
            if not cands:
                return False
            if not all(self.has_sink(c, _depth + 1, seen) for c in cands):
                return False
        return True


def check_tree(modules: List[ModuleFacts]) -> List[Violation]:
    """Pass 2: fold the index and emit A/J-series violations."""
    index = TreeIndex(modules)
    out: List[Violation] = []
    for m in modules:
        out.extend(m.blocking_in_async)          # A003 (already built)
        out.extend(_check_a001(index, m))
        out.extend(_check_a002(index, m))
        out.extend(_check_j002(m))
        out.extend(_check_j003(m))
    out.extend(_check_j001(index, modules))
    return out


def _check_a001(index: TreeIndex, m: ModuleFacts) -> List[Violation]:
    out: List[Violation] = []
    for site in m.spawns:
        if site.annotated:
            continue
        fix = ("retain the handle, use _internal.aio.spawn() "
               "(logs + counts failures), or annotate "
               "`# task ok: <why>`")
        if site.coro_term is None:
            out.append(Violation(
                rule="A001", path=m.path, line=site.line,
                scope=site.scope,
                message=("fire-and-forget task: handle dropped and the "
                         "coroutine is not statically resolvable — "
                         + fix)))
            continue
        cands = index.resolve(m.path, site.coro_term)
        if not cands:
            out.append(Violation(
                rule="A001", path=m.path, line=site.line,
                scope=site.scope,
                message=(f"fire-and-forget task {site.coro_term}(): "
                         "handle dropped and no definition found to "
                         "prove an exception sink — " + fix)))
            continue
        unsunk = [c for c in cands if not index.has_sink(c)]
        if unsunk:
            c = unsunk[0]
            out.append(Violation(
                rule="A001", path=m.path, line=site.line,
                scope=site.scope,
                message=(f"fire-and-forget task {site.coro_term}(): "
                         "handle dropped and "
                         f"{c.module}:{c.line} {c.qualname} has no "
                         "terminal exception sink (unhandled errors "
                         "vanish until loop shutdown) — " + fix)))
    return out


def _check_a002(index: TreeIndex, m: ModuleFacts) -> List[Violation]:
    out: List[Violation] = []
    for call in m.stmt_calls:
        # Only bare names / self-calls / from-imported names resolve:
        # matching arbitrary receivers' methods tree-wide by bare name
        # would drown the rule in stdlib homonyms.
        if call.recv is not None and call.recv not in ("self", "cls"):
            continue
        cands = index.resolve(m.path, call.term, tree_wide=False)
        if cands and all(c.is_async for c in cands):
            c = cands[0]
            out.append(Violation(
                rule="A002", path=m.path, line=call.line, scope=call.scope,
                message=(f"coroutine {call.term}() "
                         f"({c.module}:{c.line}) called but never "
                         "awaited or scheduled — the body never runs; "
                         "await it, or wrap it in "
                         "create_task/aio.spawn")))
    return out


def _check_j001(index: TreeIndex,
                modules: List[ModuleFacts]) -> List[Violation]:
    out: List[Violation] = []
    # Hot roots: jit-staged functions, functions annotated hot-loop, and
    # the per-step host loops — functions that call a jit-staged step
    # *inside a loop*. Loop position matters for the drivers: setup code
    # before the loop and finalization after it sync once per run, not
    # once per step, so only their in-loop syncs count; for jit-staged
    # and hot-annotated functions every sync counts (the whole body IS
    # the per-step region). Anything *reached* from a per-step call site
    # runs per step in full.
    for m in modules:
        jit_names = {f.name for f in m.funcs if f.jit} \
            | set(m.donations)
        for f in m.funcs:
            whole_body_hot = f.jit or f.hot_annotated
            driver = not whole_body_hot and any(
                in_loop and term in jit_names
                for term, in_loop in f.calls.items())
            if not (whole_body_hot or driver):
                continue
            # BFS over same-module / imported callees. (func, depth,
            # everything_counts): at depth 0 a driver only counts its
            # in-loop sites; reached callees count in full.
            seen = {id(f)}
            frontier = [(f, 0, whole_body_hot)]
            while frontier:
                cur, depth, full = frontier.pop()
                for kind, line, annotated, in_loop in cur.host_syncs:
                    if annotated or not (full or in_loop):
                        continue
                    via = "" if cur is f \
                        else f" (reached via {cur.qualname})"
                    out.append(Violation(
                        rule="J001", path=cur.module, line=line,
                        scope=cur.qualname,
                        message=(f"host-sync {kind} inside per-step hot "
                                 f"function {f.qualname}{via} — forces a "
                                 "device->host round-trip every step; "
                                 "keep values on device, batch the "
                                 "readback, or annotate "
                                 "`# host-sync ok: <why>`")))
                if depth >= _J001_DEPTH:
                    continue
                for term in sorted(cur.calls):
                    if not (full or cur.calls[term]):
                        continue   # driver's out-of-loop call: not hot
                    for cand in index.resolve(cur.module, term,
                                              tree_wide=False):
                        if id(cand) not in seen:
                            seen.add(id(cand))
                            frontier.append((cand, depth + 1, True))
    # De-dup: one site can be reachable from several hot roots.
    uniq: Dict[Tuple[str, int], Violation] = {}
    for v in out:
        uniq.setdefault((v.path, v.line), v)
    return list(uniq.values())


def _check_j002(m: ModuleFacts) -> List[Violation]:
    out: List[Violation] = []
    for f in m.funcs:
        if not f.jit:
            continue
        seen_names: Set[str] = set()
        for name, line, annotated in f.free_loads:
            # local_names is the post-walk set: a name stored ANYWHERE
            # in the function is local throughout (load-before-store is
            # an UnboundLocalError, not a closure), so filter against
            # the final set rather than walk order.
            if annotated:
                # One annotated load acknowledges the capture for the
                # whole function — don't walk the finding to the next
                # load of the same name.
                seen_names.add(name)
                continue
            if name in f.local_names:
                continue
            src = None
            if name in m.mutable_globals:
                src = f"module global (line {m.mutable_globals[name]})"
            else:
                p = f.parent
                while p is not None and src is None:
                    if name in p.mutable_locals:
                        src = (f"local of enclosing {p.qualname} "
                               f"(line {p.mutable_locals[name]})")
                    p = p.parent
            if src is not None and name not in seen_names:
                # One finding per captured name per function: the first
                # load is where the annotation goes.
                seen_names.add(name)
                out.append(Violation(
                    rule="J002", path=m.path, line=line, scope=f.qualname,
                    message=(f"jit-staged {f.name} closes over mutable "
                             f"{name!r} [{src}] — mutations after trace "
                             "are stale or force recompiles; pass it as "
                             "an argument or annotate "
                             "`# jit capture ok: <why>`")))
    return out


def _check_j003(m: ModuleFacts) -> List[Violation]:
    out: List[Violation] = []
    for call in m.donation_calls:
        if call.annotated:
            continue
        f = call.func
        stores_after = [ln for ln in f.stores.get(call.argname, ())
                        if ln >= call.line]
        rebind = min(stores_after) if stores_after else None
        for load_line in f.loads.get(call.argname, ()):
            if load_line <= call.line:
                continue
            if rebind is not None and load_line >= rebind:
                continue
            out.append(Violation(
                rule="J003", path=m.path, line=load_line, scope=call.scope,
                message=(f"{call.argname!r} read after being donated to "
                         f"{call.callee}() at line {call.line} "
                         "(donate_argnums invalidates the buffer) — "
                         "rebind the result to the same name or "
                         "annotate `# donate ok: <why>`")))
            break   # one finding per donation site is enough
    return out


def analyze_sources(sources: Dict[str, str]) -> List[Violation]:
    """Test helper: run the full two-pass analysis over in-memory
    sources ({repo-relative path: source})."""
    modules = []
    for path, src in sources.items():
        facts = collect_source(src, path)
        if facts is not None:
            modules.append(facts)
    return check_tree(modules)
