"""Event-loop stall sanitizer (the asyncio half of ``RTPU_SANITIZE``).

The io loop is ray_tpu's data plane: every RPC reply, actor dispatch,
heartbeat and serve request is a callback on one of a handful of
ray_tpu-owned loops. One callback that computes (or blocks) for 200ms
stalls *everything* behind it — the symptom shows up as tail latency
three subsystems away, with nothing in any log. rtpulint's static
A003/J001 rules catch the blocking calls they can see; this module is
the dynamic backstop that catches the ones they can't.

When armed (``RTPU_SANITIZE=1``, same switch as the lock-order
sanitizer in ``.sanitizer``), :func:`enable` patches
``asyncio.events.Handle._run`` — the single choke point every scheduled
callback and task step passes through — and times each callback run on
**registered** loops only (``IoLoopThread`` and the serve local-testing
loop register themselves; foreign loops see the real unpatched path
minus one dict probe). A run exceeding ``CONFIG.loopstall_budget_ms``
(default 50ms) is recorded in a bounded per-loop ring with:

* the stall duration,
* the callback's *creation site*: for a task step, the coroutine's
  code object (file:line qualname of the async def); for a plain
  callback, its function's code object — so the report names the
  offending coroutine, not ``Handle._run``,
* the loop's registered name.

Reporting rides the lock sanitizer's paths: the pytest plugin prints
both reports in the terminal summary, and the atexit hook prints to
stderr when anything was recorded. Overhead when off: zero (nothing
patched). When on: one dict probe per callback on unregistered loops;
two ``perf_counter`` calls on registered ones.
"""

from __future__ import annotations

import asyncio
import asyncio.events
import collections
import functools
import threading
import time
from typing import Dict, List, Optional

_RING_CAP = 128                 # stalls kept per loop (oldest dropped)

_enabled = False
_budget_ms = 50.0
_atexit_registered = False

_reg_lock = threading.Lock()
_rings: Dict[int, "collections.deque"] = {}     # id(loop) -> stall ring
_loop_names: Dict[int, str] = {}
_totals: Dict[int, int] = {}    # stalls per loop incl. ring-evicted ones

_REAL_RUN = None                # unpatched Handle._run


def _callback_site(handle) -> str:
    """Creation-site attribution for a stalled callback.

    A task step's callback is the bound ``Task.__step``; naming that
    would make every stall look identical. Unwrap to the task's
    coroutine code object instead, falling back through partials to a
    plain function's ``__code__``.
    """
    cb = getattr(handle, "_callback", None)
    owner = getattr(cb, "__self__", None)
    get_coro = getattr(owner, "get_coro", None)
    if get_coro is not None:
        try:
            coro = get_coro()
            code = getattr(coro, "cr_code", None) \
                or getattr(coro, "gi_code", None)
            if code is not None:
                return (f"{code.co_filename}:{code.co_firstlineno} "
                        f"{code.co_name}")
        except (AttributeError, TypeError):
            pass        # exotic awaitable: fall through to __code__
    func = cb
    while isinstance(func, functools.partial):
        func = func.func
    func = getattr(func, "__func__", func)      # unwrap bound methods
    code = getattr(func, "__code__", None)
    if code is not None:
        return f"{code.co_filename}:{code.co_firstlineno} {code.co_name}"
    return repr(cb)


def _patched_run(self):
    ring = _rings.get(id(getattr(self, "_loop", None)))
    if ring is None or _budget_ms <= 0:
        return _REAL_RUN(self)
    t0 = time.perf_counter()
    try:
        return _REAL_RUN(self)
    finally:
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        if elapsed_ms >= _budget_ms:
            loop_id = id(self._loop)
            site = _callback_site(self)
            with _reg_lock:
                _totals[loop_id] = _totals.get(loop_id, 0) + 1
                ring.append({"loop": _loop_names.get(loop_id, "?"),
                             "site": site, "ms": round(elapsed_ms, 2)})


def register_loop(loop: "asyncio.AbstractEventLoop", name: str = ""):
    """Opt a ray_tpu-owned loop into stall recording. No-op unless the
    sanitizer is armed — registration happens at loop construction,
    which is after process-start arming, so the ordering is safe."""
    if not _enabled:
        return
    with _reg_lock:
        _rings[id(loop)] = collections.deque(maxlen=_RING_CAP)
        _loop_names[id(loop)] = name or repr(loop)
        _totals.setdefault(id(loop), 0)


def enable(budget_ms: Optional[float] = None, register_atexit: bool = True):
    """Patch ``Handle._run``. Idempotent; call before loops register."""
    global _enabled, _budget_ms, _REAL_RUN, _atexit_registered
    if budget_ms is not None:
        _budget_ms = float(budget_ms)
    if _enabled:
        return
    _enabled = True
    if _REAL_RUN is None:
        _REAL_RUN = asyncio.events.Handle._run
    asyncio.events.Handle._run = _patched_run
    if register_atexit and not _atexit_registered:
        _atexit_registered = True
        import atexit
        atexit.register(_exit_report)


def disable():
    """Restore the real ``Handle._run`` and forget registered loops."""
    global _enabled
    if _REAL_RUN is not None:
        asyncio.events.Handle._run = _REAL_RUN
    _enabled = False
    with _reg_lock:
        _rings.clear()
        _loop_names.clear()
        _totals.clear()


def is_enabled() -> bool:
    return _enabled


def reset():
    """Clear recorded stalls (between unit-test scenarios); registered
    loops stay registered."""
    with _reg_lock:
        for ring in _rings.values():
            ring.clear()
        for k in _totals:
            _totals[k] = 0


def budget_ms() -> float:
    return _budget_ms


def report() -> dict:
    with _reg_lock:
        stalls: List[dict] = [s for ring in _rings.values() for s in ring]
        stalls.sort(key=lambda s: -s["ms"])
        return {
            "enabled": _enabled,
            "budget_ms": _budget_ms,
            "loops": len(_rings),
            "total_stalls": sum(_totals.values()),
            "stalls": stalls,
        }


def render_report(rep: Optional[dict] = None) -> str:
    rep = rep or report()
    lines = [f"event-loop stall sanitizer: {rep['loops']} loop(s) "
             f"watched, budget {rep['budget_ms']:g}ms, "
             f"{rep['total_stalls']} stall(s)"]
    for s in rep["stalls"][:20]:
        lines.append(f"  LOOP STALL {s['ms']:.1f}ms on {s['loop']}: "
                     f"{s['site']}")
    if rep["total_stalls"] > len(rep["stalls"]):
        lines.append(f"  ... ring dropped "
                     f"{rep['total_stalls'] - len(rep['stalls'])} older "
                     "stall(s)")
    if not rep["stalls"]:
        lines.append("  no stalls over budget")
    return "\n".join(lines)


def _exit_report():
    rep = report()
    if rep["total_stalls"]:
        import sys
        print(render_report(rep),  # stdout ok: atexit report
              file=sys.stderr, flush=True)


def enable_from_env() -> bool:
    """Arm iff ``RTPU_SANITIZE`` is truthy — called from
    ``sanitizer.enable_from_env()`` so every existing arming point
    (pytest plugin, worker/raylet mains) covers loop stalls too.
    Budget comes from ``CONFIG.loopstall_budget_ms`` (env-overridable
    as ``RTPU_LOOPSTALL_BUDGET_MS``); 0 disables recording."""
    import os
    if os.environ.get("RTPU_SANITIZE", "").lower() not in ("1", "true",
                                                           "yes", "on"):
        return False
    from ..config import CONFIG
    enable(budget_ms=CONFIG.loopstall_budget_ms)
    return True
