"""pytest integration for the lock-order sanitizer.

Two arming modes:

* ``RTPU_SANITIZE=1`` — enabled for the whole session (and, because the
  env var is inherited, for every raylet/worker subprocess via their
  mains). Acquisition-order cycles observed in THIS process FAIL the
  run (exit status 3): this is the CI job the acceptance criteria call
  "pass clean". Subprocess graphs live in their own processes: their
  atexit hooks print reports to stderr (forwarded by the worker log
  pump), but do not flip the exit status.
* no env var — enabled only for the duration of the concurrency-heavy
  tests (actor storm, push recovery, flat codec). Cycles are reported in
  the terminal summary but do not fail tier-1: the sanitizer is an
  opt-in gate, not a flake source.
"""

from __future__ import annotations

import os

from . import loopstall
from . import sanitizer

SANITIZED_TEST_MODULES = ("test_actor_storm", "test_push_recovery",
                          "test_flat_codec", "test_profiling",
                          "test_owner_shards", "test_log_plane",
                          "test_gcs_failover", "test_collective_ring",
                          "test_collective_backend", "test_fleet_ops",
                          "test_train_gspmd")

_env_armed = False
_ever_armed = False


def _module_name(item) -> str:
    name = os.path.basename(getattr(item, "fspath", None) and
                            str(item.fspath) or "")
    return name[:-3] if name.endswith(".py") else name


def pytest_configure(config):
    global _env_armed, _ever_armed
    if sanitizer.enable_from_env():
        _env_armed = _ever_armed = True


def pytest_runtest_setup(item):
    global _ever_armed
    if not _env_armed and _module_name(item) in SANITIZED_TEST_MODULES:
        sanitizer.enable()
        _ever_armed = True


def pytest_runtest_teardown(item, nextitem):
    if not _env_armed and sanitizer.is_enabled() \
            and _module_name(item) in SANITIZED_TEST_MODULES:
        # Stop instrumenting NEW locks outside the sanitized tests;
        # already-wrapped instances keep recording (cheap).
        sanitizer.disable()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if not _ever_armed:
        return
    rep = sanitizer.report()
    terminalreporter.write_line("")
    terminalreporter.write_line(sanitizer.render_report(rep))
    if loopstall.is_enabled():
        terminalreporter.write_line(loopstall.render_report())


def pytest_sessionfinish(session, exitstatus):
    if _env_armed and sanitizer.report()["cycles"]:
        session.exitstatus = 3
