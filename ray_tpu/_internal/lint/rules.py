"""AST rule implementations for rtpulint (see package docstring for the
rule catalog). One visitor pass per file; cross-file checks (metric
label consistency) are folded by the engine from the ``MetricDecl``
stream each file emits."""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# result types
# ---------------------------------------------------------------------------


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    scope: str          # enclosing def/class qualname ("<module>" at top)
    message: str

    @property
    def key(self) -> str:
        """Stable allowlist key: rule + file + scope (NOT the line
        number — unrelated edits must not invalidate suppressions)."""
        return f"{self.rule} {self.path}:{self.scope}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "scope": self.scope, "message": self.message,
                "key": self.key}


@dataclass
class MetricDecl:
    name: str
    kind: str           # Counter / Gauge / Histogram
    tag_keys: Tuple[str, ...]
    path: str
    line: int
    scope: str


@dataclass
class ShardTableDecl:
    """A ``self.<attr> = ... # shard-local`` declaration: the attr joins
    the cross-file registry of loop-confined owner-shard tables."""
    attr: str
    path: str
    line: int
    scope: str


@dataclass
class ShardAccess:
    """A cross-object read of a private attribute (``x._tbl`` where the
    receiver is not ``self``). The engine flags it under L007 when the
    attr is in the shard-table registry and the line lacks a
    ``# cross-shard ok:`` justification."""
    attr: str
    receiver: str
    annotated: bool
    path: str
    line: int
    scope: str


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

_METRIC_NAME_RE = re.compile(r"^rtpu_[a-z0-9_]+$")

# L001: a with-item context expression whose terminal name contains one
# of these is treated as a mutex. "cond" is deliberately absent:
# Condition bodies legitimately block in .wait().
_LOCKISH = ("lock",)

# L001: calls that block (or can block unboundedly) and therefore must
# not run while holding a lock. Matched on the full dotted form.
_BLOCKING_DOTTED = {
    "time.sleep",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "socket.create_connection",
}
# ... and on the method name alone, for receivers we cannot type
# statically: RPC stubs (.call/.call_sync), the io loop (.run_sync),
# raw sockets (.recv/.sendall/.accept).
_BLOCKING_METHODS = {"call", "call_sync", "run_sync", "recv", "sendall",
                     "accept"}
# "plasma gets": .get(...) blocks only on store-like receivers.
_BLOCKING_GET_RECEIVERS = {"store", "plasma", "_store", "_plasma"}

# L003: CONFIG attributes that are API, not flags.
_CONFIG_METHODS = {"get", "apply_system_config", "snapshot", "reset",
                   "known_flags"}

# L006: hot-path modules where a pickler on the per-call loop is a
# regression (PR 2 moved them onto the flat-wire codec; PR 11 added the
# receive-side decode module).
_HOT_PATH_FILES = {
    "ray_tpu/_internal/rpc.py",
    "ray_tpu/_internal/task_spec.py",
    "ray_tpu/_internal/core_worker.py",
    "ray_tpu/_internal/native_decode.py",
}
_PICKLER_RECEIVERS = {"serialization", "cloudpickle", "pickle"}
# L006b: the batch-scoped pickle entry points (serialization.dumps_batch
# / loads_batch) are allowed on hot paths ONLY with a same-line
# `# batch ok: <why the cost is per batch, not per call>` annotation —
# the rule keeps "batch" honest instead of becoming a rename loophole.
_PICKLER_BATCH_TERMS = {"dumps_batch", "loads_batch"}
_BATCH_OK_MARK = "# batch ok"

# L005: the registry module itself creates the threads.
_THREADS_HELPER_FILE = "ray_tpu/_internal/threads.py"
_THREAD_REGISTER_FUNCS = {"register_daemon_thread", "spawn_daemon"}

# L007: ambient-loop lookups are banned in _internal/ — with owner
# shards there is more than one loop per process, so "the" event loop
# is whichever thread you happen to be on (and get_event_loop() is
# deprecated outside a running loop under 3.12 anyway). Use
# get_running_loop(), an explicit loop handle, or the shard mailbox.
_L007_DIR = "ray_tpu/_internal/"
_SHARD_LOCAL_MARK = "# shard-local"
_CROSS_SHARD_MARK = "# cross-shard ok"

# L008: logging hygiene — _internal/ output goes through the structured
# logger (the log plane stamps and retains it); a bare print() bypasses
# attribution and ring capture. __main__ entrypoints and explicitly
# annotated protocol/CLI writes are exempt.
_STDOUT_OK_MARK = "# stdout ok"

# L009: retry backoff — a raw time.sleep/asyncio.sleep inside an except
# handler inside a loop is a hand-rolled retry loop; those sleep
# schedules must come from backoff.Backoff (jittered exponential, cap,
# deadline) so retry storms across the fleet don't synchronize. The
# implementation module itself is exempt; deliberate fixed-period waits
# annotate the line `# backoff ok: <why>`.
_BACKOFF_OK_MARK = "# backoff ok"
_BACKOFF_IMPL_FILE = "ray_tpu/_internal/backoff.py"
_SLEEP_DOTTED = {"time.sleep", "asyncio.sleep"}
# L009 also covers the reconciler loops OUTSIDE _internal/: the
# autoscaler (config-driven Monitor + the elastic metric-driven
# reconciler) and the serve control plane both run forever against a
# control plane that fails over — their error paths must ride the same
# jittered schedule or a GCS restart synchronizes a fleet-wide retry
# storm.
_L009_EXTRA_DIRS = ("ray_tpu/autoscaler/", "ray_tpu/serve/_private/")


def _dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as "a.b.c" (None if not a chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def _is_lockish(expr: ast.AST) -> bool:
    term = _terminal(_dotted(expr)).lower()
    return bool(term) and any(s in term for s in _LOCKISH)


def _broad_handler(handler: ast.ExceptHandler) -> Optional[str]:
    """Return "bare" / "Exception" / "BaseException" when the handler
    catches everything, else None. Tuples count if any member is broad."""
    t = handler.type
    if t is None:
        return "bare"
    names = []
    if isinstance(t, ast.Tuple):
        names = [_terminal(_dotted(e)) for e in t.elts]
    else:
        names = [_terminal(_dotted(t))]
    for n in names:
        if n in ("Exception", "BaseException"):
            return n
    return None


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Scope:
    __slots__ = ("name", "node", "lock_depth")

    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        # with-lock nesting INSIDE this scope only: a closure defined
        # under `with lock:` does not run while the lock is held.
        self.lock_depth = 0


# ---------------------------------------------------------------------------
# the per-file visitor
# ---------------------------------------------------------------------------


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, known_flags: Sequence[str],
                 bootstrap_env: Sequence[str],
                 src_lines: Optional[Sequence[str]] = None):
        self.path = path
        self.known_flags = frozenset(known_flags)
        self.bootstrap_env = frozenset(bootstrap_env)
        self.violations: List[Violation] = []
        self.metric_decls: List[MetricDecl] = []
        self.shard_decls: List[ShardTableDecl] = []
        self.shard_accesses: List[ShardAccess] = []
        self._lines: Sequence[str] = src_lines or ()
        self._scopes: List[_Scope] = [_Scope("<module>", None)]
        self._metric_aliases: set = set()   # Counter/... imported from metrics
        self._loop_depth = 0
        self._except_depth = 0
        self._hot_path = path in _HOT_PATH_FILES
        self._is_threads_helper = path == _THREADS_HELPER_FILE
        self._is_config = path == "ray_tpu/_internal/config.py"
        self._internal = path.startswith(_L007_DIR)
        self._is_main_entry = path.endswith("__main__.py")

    # -- bookkeeping --------------------------------------------------------

    @property
    def scope(self) -> str:
        names = [s.name for s in self._scopes[1:]]
        return ".".join(names) if names else "<module>"

    def _emit(self, rule: str, node: ast.AST, message: str):
        self.violations.append(Violation(
            rule=rule, path=self.path, line=getattr(node, "lineno", 0),
            scope=self.scope, message=message))

    def _in_lock(self) -> bool:
        return self._scopes[-1].lock_depth > 0

    # -- imports: track metric constructor aliases --------------------------

    def visit_ImportFrom(self, node: ast.ImportFrom):
        mod = node.module or ""
        if mod.endswith("metrics") or mod.endswith("util.metrics"):
            for alias in node.names:
                if alias.name in ("Counter", "Gauge", "Histogram"):
                    self._metric_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    # -- scope / context stack ----------------------------------------------

    def _visit_scoped(self, node, name: str):
        self._scopes.append(_Scope(name, node))
        outer_loop, self._loop_depth = self._loop_depth, 0
        # A closure defined inside an except handler does not RUN there.
        outer_except, self._except_depth = self._except_depth, 0
        self.generic_visit(node)
        self._loop_depth = outer_loop
        self._except_depth = outer_except
        self._scopes.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._visit_scoped(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._visit_scoped(node, node.name)

    def visit_ClassDef(self, node: ast.ClassDef):
        self._visit_scoped(node, node.name)

    def visit_Lambda(self, node: ast.Lambda):
        self._visit_scoped(node, "<lambda>")

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _visit_loop

    def visit_With(self, node: ast.With):
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith):
        self._visit_with(node)

    def _visit_with(self, node):
        holds = any(_is_lockish(item.context_expr) for item in node.items)
        for item in node.items:
            self.visit(item)
        if holds:
            self._scopes[-1].lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if holds:
            self._scopes[-1].lock_depth -= 1

    # -- L002: swallowed exceptions -----------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        broad = _broad_handler(node)
        if broad is not None and all(
                isinstance(s, (ast.Pass, ast.Continue)) for s in node.body):
            what = "bare except:" if broad == "bare" \
                else f"except {broad}:"
            self._emit("L002", node,
                       f"{what} silently swallows — log at debug level, "
                       "narrow the exception type, or allowlist with a "
                       "justification")
        self._except_depth += 1
        self.generic_visit(node)
        self._except_depth -= 1

    # -- L003 (CONFIG side) --------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "CONFIG" \
                and not self._is_config:
            attr = node.attr
            if not attr.startswith("_") and attr not in _CONFIG_METHODS \
                    and attr not in self.known_flags:
                self._emit("L003", node,
                           f"CONFIG.{attr} is not registered in "
                           "config._DEFAULTS (typo'd flag?)")
        # L007b candidate: a private attribute read through a receiver
        # other than bare `self` (cross-object). Recorded for the
        # engine's cross-file fold against the shard-table registry —
        # _internal/ only, like L007a: matching is by bare attribute
        # name, and an unrelated `_running`/`_actors` in user-facing
        # code must not trip shard-confinement findings.
        if self._internal and node.attr.startswith("_") and not (
                isinstance(node.value, ast.Name)
                and node.value.id in ("self", "cls")):
            recv = _dotted(node.value)
            if recv is not None:
                self.shard_accesses.append(ShardAccess(
                    attr=node.attr, receiver=recv,
                    annotated=self._line_marked(node, _CROSS_SHARD_MARK),
                    path=self.path, line=node.lineno, scope=self.scope))
        self.generic_visit(node)

    def _line_marked(self, node: ast.AST, mark: str) -> bool:
        line = getattr(node, "lineno", 0)
        if 0 < line <= len(self._lines):
            return mark in self._lines[line - 1]
        return False

    # -- L007a: shard-local table declarations ------------------------------

    def _maybe_shard_decl(self, node: ast.AST, target: ast.AST):
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self" \
                and self._line_marked(node, _SHARD_LOCAL_MARK):
            self.shard_decls.append(ShardTableDecl(
                attr=target.attr, path=self.path, line=node.lineno,
                scope=self.scope))

    def visit_Assign(self, node: ast.Assign):
        for target in node.targets:
            self._maybe_shard_decl(node, target)
        self._check_logger_naming(node)
        self.generic_visit(node)

    def _check_logger_naming(self, node: ast.Assign):
        """L008c: the module-level ``logging.getLogger(__name__)``
        handle is named ``logger`` everywhere in _internal/ — one
        spelling for greps, docs, and the log plane's conventions."""
        if not self._internal or len(self._scopes) > 1:
            return
        value = node.value
        if isinstance(value, ast.Call) \
                and _dotted(value.func) in ("logging.getLogger",
                                            "getLogger") \
                and value.args \
                and isinstance(value.args[0], ast.Name) \
                and value.args[0].id == "__name__":
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id != "logger":
                    self._emit(
                        "L008", node,
                        f"module-level logger handle named "
                        f"{target.id!r} — the convention is `logger = "
                        "logging.getLogger(__name__)`")

    def visit_AnnAssign(self, node: ast.AnnAssign):
        self._maybe_shard_decl(node, node.target)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        recv = _dotted(node.value)
        if recv in ("os.environ", "environ"):
            key = _str_const(node.slice)
            if key is not None:
                self._check_env_key(node, key)
        self.generic_visit(node)

    def _check_env_key(self, node: ast.AST, key: str):
        if not key.startswith("RTPU_") or self._is_config:
            return
        if key in self.bootstrap_env:
            return
        flag = key[len("RTPU_"):].lower()
        if flag not in self.known_flags:
            self._emit("L003", node,
                       f"env read of {key!r} resolves to neither a "
                       "config._DEFAULTS flag nor config.BOOTSTRAP_ENV "
                       "(typo'd kill switch?)")

    # -- the big Call dispatcher --------------------------------------------

    def visit_Call(self, node: ast.Call):
        dotted = _dotted(node.func)
        term = _terminal(dotted)

        # L003: os.environ.get("RTPU_X") / os.getenv("RTPU_X")
        if term in ("get", "getenv"):
            recv = _dotted(node.func.value) \
                if isinstance(node.func, ast.Attribute) else None
            if (recv in ("os.environ", "environ")
                    or dotted == "os.getenv") and node.args:
                key = _str_const(node.args[0])
                if key is not None:
                    self._check_env_key(node, key)

        # L001a: explicit lock acquire outside try/finally-with-release
        if term == "acquire" and isinstance(node.func, ast.Attribute) \
                and _is_lockish(node.func.value):
            if not self._acquire_is_protected(node):
                self._emit("L001", node,
                           f"{_dotted(node.func.value)}.acquire() outside "
                           "`with` / try-finally — a failure between "
                           "acquire and release leaks the lock")

        # L001b: blocking call while holding a lock
        if self._in_lock():
            blocking = dotted in _BLOCKING_DOTTED \
                or term in _BLOCKING_METHODS \
                or (term == "get" and isinstance(node.func, ast.Attribute)
                    and _terminal(_dotted(node.func.value)).lower()
                    in _BLOCKING_GET_RECEIVERS)
            if blocking:
                self._emit("L001", node,
                           f"blocking call {dotted or term}() inside a "
                           "`with <lock>:` body — move it outside the "
                           "critical section")

        # L004: metric construction
        if isinstance(node.func, ast.Name) \
                and node.func.id in self._metric_aliases:
            self._check_metric_ctor(node, node.func.id)

        # L005: raw daemon thread
        if term == "Thread" and not self._is_threads_helper:
            for kw in node.keywords:
                if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    if not self._scope_registers_thread():
                        self._emit(
                            "L005", node,
                            "daemon Thread with no shutdown story — use "
                            "threads.spawn_daemon() or pass it to "
                            "threads.register_daemon_thread() in the same "
                            "scope")
                    break

        # L007a: ambient-loop lookup in _internal/ — with owner shards
        # more than one loop exists per process, so the ambient loop is
        # whichever thread you happen to be on.
        if self._internal and term == "get_event_loop" \
                and dotted in ("asyncio.get_event_loop",
                               "get_event_loop"):
            self._emit("L007", node,
                       "asyncio.get_event_loop() is ambient-loop — use "
                       "asyncio.get_running_loop(), an explicit loop "
                       "handle (CoreWorker._serve_loop / OwnerShard."
                       "loop), or the shard mailbox")

        # L008a: bare print() in _internal/ — output must go through
        # the structured logger (stamped + ring-captured by the log
        # plane) or carry an explicit `# stdout ok:` annotation.
        if self._internal and not self._is_main_entry \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "print" \
                and not self._line_marked(node, _STDOUT_OK_MARK):
            self._emit("L008", node,
                       "bare print() in _internal/ bypasses log "
                       "attribution and ring capture — use "
                       "logger.<level>(), or annotate the line "
                       "`# stdout ok: <why this is protocol/CLI "
                       "output>`")

        # L008b: loggers must be module-named — getLogger with a
        # literal breaks per-module filtering and the __name__
        # convention the log plane documents.
        if self._internal and term == "getLogger" \
                and dotted in ("logging.getLogger", "getLogger") \
                and node.args \
                and not (isinstance(node.args[0], ast.Name)
                         and node.args[0].id == "__name__"):
            self._emit("L008", node,
                       "logging.getLogger() in _internal/ must be "
                       "getLogger(__name__) (or argless for the root "
                       "logger)")

        # L009: raw sleep in a retry loop (sleep-on-error inside a loop)
        # in _internal/ or a reconciler package (autoscaler, serve
        # control plane) — retry schedules come from backoff.Backoff so
        # fleet-wide retry storms stay jittered, capped and bounded.
        if (self._internal
                or self.path.startswith(_L009_EXTRA_DIRS)) \
                and self.path != _BACKOFF_IMPL_FILE \
                and dotted in _SLEEP_DOTTED \
                and self._loop_depth > 0 and self._except_depth > 0 \
                and not self._line_marked(node, _BACKOFF_OK_MARK):
            self._emit("L009", node,
                       f"{dotted}() on the error path of a retry loop — "
                       "use backoff.Backoff (jittered exponential, cap, "
                       "deadline), or annotate the line "
                       "`# backoff ok: <why a raw sleep is right>`")

        # L006: pickler on a hot-path module
        if self._hot_path and term in ("dumps", "loads") \
                and isinstance(node.func, ast.Attribute) \
                and _terminal(_dotted(node.func.value)) \
                in _PICKLER_RECEIVERS:
            self._emit("L006", node,
                       f"{dotted}() in hot-path module — per-call task "
                       "encoding must use the flat-wire codec; pickle "
                       "belongs behind the fallback gate (allowlist with "
                       "justification if this IS the gate)")

        # L006b: batch-scoped pickler on a hot-path module without its
        # justification mark
        if self._hot_path and term in _PICKLER_BATCH_TERMS \
                and isinstance(node.func, ast.Attribute) \
                and _terminal(_dotted(node.func.value)) \
                in _PICKLER_RECEIVERS \
                and not self._line_marked(node, _BATCH_OK_MARK):
            self._emit("L006", node,
                       f"{dotted}() in hot-path module without a "
                       "`# batch ok: <why>` annotation — batch-scoped "
                       "pickling is allowed only where one call covers "
                       "a whole batch of completions, and the line must "
                       "say so")

        self.generic_visit(node)

    # -- rule helpers --------------------------------------------------------

    def _acquire_is_protected(self, call: ast.Call) -> bool:
        """True when the acquire is paired with a structural release:
        an enclosing Try whose finalbody calls .release(), or a
        non-blocking conditional acquire (`if lock.acquire(False):` /
        `acquire(timeout=...)` used as a test)."""
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value is False:
            return True
        for kw in call.keywords:
            if kw.arg in ("blocking", "timeout"):
                return True
        node = self._scopes[-1].node
        # Search this scope for a Try whose finalbody releases and that
        # either covers the call (`with`-less acquire inside try) or
        # starts right after it (the classic `acquire(); try: ...
        # finally: release()` — the acquire precedes the Try node).
        # (ast has no parent links and the per-scope subtree is small,
        # so a walk is fine.)
        root = node if node is not None else self._module
        for t in ast.walk(root):
            if isinstance(t, ast.Try) and t.finalbody \
                    and call.lineno \
                    <= (getattr(t, "end_lineno", None) or t.lineno):
                for sub in t.finalbody:
                    for c in ast.walk(sub):
                        if isinstance(c, ast.Call) \
                                and isinstance(c.func, ast.Attribute) \
                                and c.func.attr == "release":
                            return True
        return False

    def _scope_registers_thread(self) -> bool:
        """L005: does the innermost function scope (or module) also call
        register_daemon_thread/spawn_daemon?"""
        node = self._scopes[-1].node
        root = node if node is not None else self._module
        for c in ast.walk(root):
            if isinstance(c, ast.Call) \
                    and _terminal(_dotted(c.func)) in _THREAD_REGISTER_FUNCS:
                return True
        return False

    def _check_metric_ctor(self, node: ast.Call, kind: str):
        name = _str_const(node.args[0]) if node.args else None
        if name is None:
            self._emit("L004", node,
                       f"{kind}() series name must be a string literal "
                       "(the linter cross-checks label sets by name)")
            return
        if not _METRIC_NAME_RE.match(name):
            self._emit("L004", node,
                       f"{kind} name {name!r} must match rtpu_[a-z0-9_]+")
        if self._loop_depth:
            self._emit("L004", node,
                       f"{kind}({name!r}) constructed inside a loop — "
                       "series registration is once-per-process, hoist it")
        elif not self._construction_site_ok():
            self._emit("L004", node,
                       f"{kind}({name!r}) constructed per-call — create "
                       "at module scope, in a LazyMetrics _build*() "
                       "factory, or behind an `is None` once-guard")
        tag_keys: Tuple[str, ...] = ()
        literal = True
        for kw in node.keywords:
            if kw.arg == "tag_keys":
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    vals = [_str_const(e) for e in kw.value.elts]
                    if all(v is not None for v in vals):
                        tag_keys = tuple(vals)
                    else:
                        literal = False
                else:
                    literal = False
        if literal:
            self.metric_decls.append(MetricDecl(
                name=name, kind=kind, tag_keys=tag_keys, path=self.path,
                line=node.lineno, scope=self.scope))

    def _construction_site_ok(self) -> bool:
        """Metric constructors are once-per-process when at module/class
        scope, in a ``_build*`` factory (the LazyMetrics idiom), or under
        an ``is None`` once-guard anywhere in the enclosing function."""
        func = None
        for s in self._scopes[1:]:
            if isinstance(s.node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                func = s
        if func is None:
            return True
        if func.name.startswith("_build") or func.name.startswith("build"):
            return True
        for n in ast.walk(func.node):
            if isinstance(n, ast.Compare) and any(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in n.ops) and any(
                    isinstance(c, ast.Constant) and c.value is None
                    for c in [n.left, *n.comparators]):
                return True
        return False

    # -- entry ---------------------------------------------------------------

    def run(self, tree: ast.Module):
        self._module = tree
        self.visit(tree)
        return (self.violations, self.metric_decls, self.shard_decls,
                self.shard_accesses)


def _project_tables() -> Tuple[frozenset, frozenset]:
    from ..config import BOOTSTRAP_ENV, CONFIG
    return frozenset(CONFIG.known_flags()), frozenset(BOOTSTRAP_ENV)


def lint_source(src: str, path: str,
                known_flags: Optional[Sequence[str]] = None,
                bootstrap_env: Optional[Sequence[str]] = None,
                tree: Optional[ast.Module] = None,
                ) -> Tuple[List[Violation], List[MetricDecl],
                           List[ShardTableDecl], List[ShardAccess]]:
    """Lint one file's source. ``path`` must be repo-relative with
    forward slashes (it selects per-module rule behavior and becomes the
    allowlist key). Pass ``tree`` to reuse an AST the caller already
    parsed (the engine shares one parse between this visitor and the
    cross-module pass)."""
    if known_flags is None or bootstrap_env is None:
        flags, env = _project_tables()
        known_flags = known_flags if known_flags is not None else flags
        bootstrap_env = bootstrap_env if bootstrap_env is not None else env
    if tree is None:
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            return [Violation(rule="L000", path=path, line=e.lineno or 0,
                              scope="<module>",
                              message=f"syntax error: {e.msg}")], [], [], []
    return _Linter(path, known_flags, bootstrap_env,
                   src_lines=src.splitlines()).run(tree)


def check_shard_confinement(decls: List[ShardTableDecl],
                            accesses: List[ShardAccess]
                            ) -> List[Violation]:
    """L007b cross-file fold: every cross-object read of a registered
    ``# shard-local`` table must carry a ``# cross-shard ok:``
    justification on the same line — those tables are loop-confined, and
    an unannotated foreign read is either a data race or an unreviewed
    observability peek."""
    registry = {d.attr for d in decls}
    out: List[Violation] = []
    for a in accesses:
        if a.attr in registry and not a.annotated:
            out.append(Violation(
                rule="L007", path=a.path, line=a.line, scope=a.scope,
                message=(f"{a.receiver}.{a.attr} reads a shard-local "
                         "table across objects — route through the "
                         "owning shard's mailbox, or annotate the line "
                         "`# cross-shard ok: <why this race is safe>`")))
    return out
