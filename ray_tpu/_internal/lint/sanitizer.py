"""Runtime lock-order sanitizer (the dynamic half of rtpulint).

``RTPU_SANITIZE=1`` (or an explicit :func:`enable`) replaces
``threading.Lock``/``RLock`` with a factory that hands **ray_tpu
modules** an instrumented proxy (everyone else keeps the real thing —
the factory checks the caller's module, so third-party code and the
interpreter's own locks are untouched). The proxy:

* keeps a per-thread held-lock list,
* on every acquire while other locks are held, adds an edge
  ``held_site -> acquired_site`` to a global lock-acquisition-order
  graph keyed by lock *creation site* (module:line — all instances born
  at one site share a node, so an AB/BA inversion between two actor
  instances is still one cycle),
* records **blocked-while-holding** waits: the acquire first tries
  non-blocking; a miss while the thread holds another lock is a
  latent-convoy/deadlock datapoint even when it later succeeds.

:func:`report` returns cycles in the order graph (potential deadlocks —
the classic AB/BA inversion shows up as a 2-cycle without ever actually
deadlocking the test) plus the blocked-wait list. With the env var set a
process-exit hook prints the report to stderr; the pytest plugin
(``.pytest_plugin``) surfaces it per test session instead.

Overhead when off: zero — nothing is patched, no proxy exists. When on:
one dict/list op per acquire/release plus one set-add per held pair.

Reentrant same-instance acquires (RLock) record nothing; same-*site*
nesting across distinct instances is tracked separately
(``nested_same_site``) and excluded from cycle detection — ordering
within one site (e.g. per-dep-list refcount locks) needs an instance
key, which would make every report nondeterministic.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_enabled = False
_prefixes: Tuple[str, ...] = ("ray_tpu",)
_atexit_registered = False

_graph_lock = _REAL_LOCK()
_edges: Dict[Tuple[str, str], int] = {}       # (held, acquired) -> count
_sites: Set[str] = set()
_nested_same_site: Dict[str, int] = {}
_blocked: Dict[Tuple[str, Tuple[str, ...]], int] = {}

_tls = threading.local()


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []          # [(site, instance_id), ...]
    return held


class LockProxy:
    """Instrumented Lock/RLock wrapper. API-compatible with the real
    thing (acquire/release/locked/context manager)."""

    __slots__ = ("_inner", "_site", "_reentrant")

    def __init__(self, inner, site: str, reentrant: bool = False):
        self._inner = inner
        self._site = site
        self._reentrant = reentrant
        with _graph_lock:
            _sites.add(site)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = _held()
        me = id(self)
        if self._reentrant and any(i == me for _, i in held):
            # Pure reentry: no ordering information, don't re-record.
            got = self._inner.acquire(blocking, timeout)
            if got:
                held.append((self._site, me))
            return got
        if not blocking:
            # Try-lock: a failed non-blocking acquire cannot deadlock,
            # and threading.Condition._is_owned() probes acquire(False)
            # on the lock its OWN thread holds — recording it would fill
            # the report with spurious nested/blocked entries on every
            # Condition.wait()/notify().
            got = self._inner.acquire(False)
            if got:
                held.append((self._site, me))
            return got
        if held:
            with _graph_lock:
                for held_site, held_id in held:
                    if held_site == self._site:
                        _nested_same_site[self._site] = \
                            _nested_same_site.get(self._site, 0) + 1
                    else:
                        key = (held_site, self._site)
                        _edges[key] = _edges.get(key, 0) + 1
        got = self._inner.acquire(False)
        if not got:
            if held:
                key = (self._site, tuple(s for s, _ in held))
                with _graph_lock:
                    _blocked[key] = _blocked.get(key, 0) + 1
            got = self._inner.acquire(True, timeout)
        if got:
            held.append((self._site, me))
        return got

    def release(self):
        held = _held()
        me = id(self)
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] == me:
                del held[i]
                break
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<LockProxy site={self._site} {self._inner!r}>"


def _caller_site(depth: int = 2) -> Tuple[str, bool]:
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return "<unknown>", False
    mod = frame.f_globals.get("__name__", "")
    site = f"{mod}:{frame.f_lineno}"
    return site, any(mod == p or mod.startswith(p + ".")
                     for p in _prefixes)


def _make_lock():
    site, instrument = _caller_site()
    inner = _REAL_LOCK()
    return LockProxy(inner, site) if instrument else inner


def _make_rlock():
    site, instrument = _caller_site()
    inner = _REAL_RLOCK()
    return LockProxy(inner, site, reentrant=True) if instrument else inner


def instrument(inner=None, site: str = "<explicit>",
               reentrant: bool = False) -> LockProxy:
    """Wrap one lock explicitly (unit tests; sanitizing a lock created
    before :func:`enable` ran)."""
    if inner is None:
        inner = _REAL_RLOCK() if reentrant else _REAL_LOCK()
    return LockProxy(inner, site, reentrant=reentrant)


def enable(prefixes: Optional[Tuple[str, ...]] = None,
           register_atexit: bool = True):
    """Patch threading.Lock/RLock. Idempotent; thread-unsafe by design
    (call it before spawning workers — the pytest plugin and worker_main
    both do)."""
    global _enabled, _prefixes, _atexit_registered
    if prefixes:
        _prefixes = tuple(prefixes)
    if _enabled:
        return
    _enabled = True
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    if register_atexit and not _atexit_registered:
        _atexit_registered = True
        import atexit
        atexit.register(_exit_report)


def disable():
    """Restore the real constructors. Already-instrumented instances
    keep recording (cheap, and their data stays comparable)."""
    global _enabled
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def reset():
    """Clear the recorded graph (between unit-test scenarios)."""
    with _graph_lock:
        _edges.clear()
        _sites.clear()
        _nested_same_site.clear()
        _blocked.clear()


def find_cycles() -> List[List[str]]:
    """Elementary cycles in the site order graph via iterative DFS over
    strongly-reachable back edges. Deterministic (sorted adjacency);
    each cycle reported once, rotated to its smallest node."""
    with _graph_lock:
        adj: Dict[str, List[str]] = {}
        for (a, b) in _edges:
            adj.setdefault(a, []).append(b)
        for v in adj.values():
            v.sort()
    seen_cycles: Set[Tuple[str, ...]] = set()
    cycles: List[List[str]] = []
    for start in sorted(adj):
        # DFS from `start`, only visiting nodes >= start so each cycle
        # is found from its smallest node exactly once.
        stack = [(start, iter(adj.get(start, ())))]
        path = [start]
        on_path = {start}
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if nxt < start:
                    continue
                if nxt == start:
                    cyc = tuple(path)
                    if cyc not in seen_cycles:
                        seen_cycles.add(cyc)
                        cycles.append(list(cyc) + [start])
                elif nxt not in on_path:
                    stack.append((nxt, iter(adj.get(nxt, ()))))
                    path.append(nxt)
                    on_path.add(nxt)
                    advanced = True
                    break
            if not advanced:
                stack.pop()
                on_path.discard(path.pop())
    return cycles


def report() -> dict:
    cycles = find_cycles()
    with _graph_lock:
        blocked = [{"lock": site, "while_holding": list(held),
                    "count": count}
                   for (site, held), count in sorted(_blocked.items())]
        return {
            "enabled": _enabled,
            "locks": len(_sites),
            "edges": len(_edges),
            "cycles": cycles,
            "blocked_while_holding": blocked,
            "nested_same_site": dict(sorted(_nested_same_site.items())),
        }


def render_report(rep: Optional[dict] = None) -> str:
    rep = rep or report()
    lines = [f"lock-order sanitizer: {rep['locks']} lock sites, "
             f"{rep['edges']} order edges"]
    for cyc in rep["cycles"]:
        lines.append("  POTENTIAL DEADLOCK (acquisition-order cycle): "
                     + " -> ".join(cyc))
    for b in rep["blocked_while_holding"]:
        lines.append(f"  blocked x{b['count']} on {b['lock']} while "
                     f"holding {b['while_holding']}")
    if not rep["cycles"]:
        lines.append("  no cycles detected")
    return "\n".join(lines)


def _exit_report():
    rep = report()
    if rep["cycles"] or rep["blocked_while_holding"]:
        # atexit report: logging may already be torn down
        print(render_report(rep),  # stdout ok: atexit report
              file=sys.stderr, flush=True)


def enable_from_env():
    """Enable iff RTPU_SANITIZE is truthy (the worker/raylet mains call
    this so sanitized runs cover every process in the cluster). Arms
    the event-loop stall sanitizer (.loopstall) off the same switch so
    one env var covers both dynamic checkers in every process."""
    if os.environ.get("RTPU_SANITIZE", "").lower() in ("1", "true", "yes",
                                                       "on"):
        enable()
        from . import loopstall
        loopstall.enable_from_env()
        return True
    return False
