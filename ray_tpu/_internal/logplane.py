"""Fleet log & failure-forensics plane: the fifth observability leg
(PR 1 time, PR 3 memory, PR 5 CPU, PR 7 accelerator, this module LOGS).

Three layers (reference: _private/log_monitor.py + the dashboard log
view + state API ``list_logs``/``get_log``):

* **Capture** — a worker process stamps every stdout/stderr line and
  every ``logging`` record with its attribution ``(task_id, actor_id,
  job, level)`` before the bytes hit the pipe (the raylet already knows
  node/pid). The stamp rides as an in-band prefix the raylet's log pump
  strips, so driver-visible output is unchanged. Attribution reuses the
  executor thread→spec registry the profiler maintains
  (:data:`profiler._CURRENT_TASKS`), so a ``print()`` inside a task
  body carries that task's id with zero extra per-task bookkeeping.

* **Retention** — the raylet keeps a bounded per-worker
  :class:`LogRing` (size-capped deque + drop counter), so lines are
  retained and queryable cluster-wide *even with* ``log_to_driver``
  *off* (the old DEVNULL path becomes ring-only capture; pubsub
  forwarding to drivers stays the opt-in streaming path).

* **Forensics** — on worker death the raylet assembles a postmortem:
  exit-code/signal taxonomy (:func:`classify_exit` — OOM-kill,
  segfault, ``sys.exit``, uncaught exception), the ring's last N lines,
  the stuck-task stack-dump file if one was captured, and recently seen
  task ids. The report lands on the ``WORKER_DIED`` GCS event and is
  threaded into the :class:`~.errors.WorkerCrashedError` /
  ``ActorDiedError`` raised to callers, so a dead worker's last words
  arrive *in the driver's exception*.

Kill switch: ``RTPU_NO_LOG_PLANE=1`` — no stream wrappers, no rings,
exact-legacy pump wiring (DEVNULL when ``log_to_driver`` is off), zero
extra threads.
"""

from __future__ import annotations

import logging
import os
import re
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from .config import CONFIG

logger = logging.getLogger(__name__)

# In-band stamp framing: \x1d (ASCII group separator — never produced
# by normal text output) brackets the attribution fields.
#   \x1d<task>|<actor>|<job>|<LEVEL>\x1d<message>
# Empty fields are omitted but the pipes stay, so parsing is a fixed
# 2-split + 3-partition with no regex on the hot path.
STAMP_SEP = "\x1d"

_LEVEL_RANK = {"DEBUG": 10, "INFO": 20, "WARNING": 30, "ERROR": 40,
               "CRITICAL": 50}


def level_rank(level: Optional[str]) -> int:
    return _LEVEL_RANK.get((level or "INFO").upper(), 20)


def plane_disabled() -> bool:
    return CONFIG.no_log_plane


# ---------------------------------------------------------------------------
# worker-side capture: stamp attribution onto every line
# ---------------------------------------------------------------------------


def current_attribution() -> Tuple[str, str, str]:
    """``(task_hex, actor_hex, job_hex)`` of the task executing on the
    CALLING thread ("" when idle). Reads the profiler's executor
    registry racily — same tolerance as stack sampling: a recycled spec
    can at worst mis-attribute one line."""
    from . import profiler
    spec = profiler._CURRENT_TASKS.get(threading.get_ident())
    if spec is None:
        return ("", "", "")
    try:
        task = spec.task_id.hex()
        actor = spec.actor_id.hex() if spec.actor_id is not None else ""
        job = spec.job_id.hex() if spec.job_id is not None else ""
        return (task, actor, job)
    except Exception:  # noqa: BLE001 — racing a freelist recycle
        return ("", "", "")


def stamp_line(line: str, level: str) -> str:
    task, actor, job = current_attribution()
    return f"{STAMP_SEP}{task}|{actor}|{job}|{level}{STAMP_SEP}{line}"


def parse_line(raw: str) -> Tuple[Dict[str, Optional[str]], str]:
    """Split one pumped line into ``(attribution, message)``. Unstamped
    lines (faulthandler writing to fd 2, subprocesses the task spawned)
    come back with empty attribution."""
    if not raw.startswith(STAMP_SEP):
        return ({"task": None, "actor": None, "job": None,
                 "level": None}, raw)
    end = raw.find(STAMP_SEP, 1)
    if end < 0:
        return ({"task": None, "actor": None, "job": None,
                 "level": None}, raw)
    fields = raw[1:end].split("|")
    if len(fields) != 4:
        return ({"task": None, "actor": None, "job": None,
                 "level": None}, raw)
    task, actor, job, level = fields
    return ({"task": task or None, "actor": actor or None,
             "job": job or None, "level": level or None}, raw[end + 1:])


class _StampingStream:
    """TextIO proxy over the worker's real stdout/stderr: buffers until
    a newline, then writes the stamped line through in ONE underlying
    write (pipe writes under PIPE_BUF are atomic, so concurrently
    printing threads don't shear each other's stamps)."""

    def __init__(self, raw, default_level: str):
        self._raw = raw
        self._level = default_level
        self._pending = ""
        # flush() emitted a STAMPED partial line whose newline has not
        # arrived yet: the continuation must go out raw (no second
        # stamp), or the pump's line reassembly would leave stamp bytes
        # embedded mid-message.
        self._midline = False
        self._lock = threading.Lock()

    def write(self, text) -> int:
        if not isinstance(text, str):
            text = str(text)
        with self._lock:
            self._pending += text
            if "\n" not in self._pending:
                return len(text)
            *lines, self._pending = self._pending.split("\n")
            parts = []
            for line in lines:
                if self._midline:
                    parts.append(line + "\n")  # completes a flushed stamp
                    self._midline = False
                else:
                    parts.append(stamp_line(line, self._level) + "\n")
            out = "".join(parts)
        try:
            self._raw.write(out)
            self._raw.flush()
        except (ValueError, OSError):
            logger.debug("stamped write to closed stream dropped",
                         exc_info=True)
        return len(text)

    def flush(self):
        with self._lock:
            pending, self._pending = self._pending, ""
            if pending:
                # progress output (print(..., end="", flush=True)) goes
                # through now; the eventual newline (or the next flush)
                # continues this SAME stamped line raw
                pending = pending if self._midline \
                    else stamp_line(pending, self._level)
                self._midline = True
        try:
            if pending:
                self._raw.write(pending)
            self._raw.flush()
        except (ValueError, OSError):
            logger.debug("stamped flush to closed stream dropped",
                         exc_info=True)

    def fileno(self):
        return self._raw.fileno()

    def isatty(self):
        return False

    @property
    def raw(self):
        return self._raw

    def __getattr__(self, name):
        return getattr(self._raw, name)


class _StampingLogHandler(logging.Handler):
    """Root handler for worker processes: stamps each record with its
    REAL level (a raw ``print`` only gets the stream default) and
    writes to the ORIGINAL stderr, bypassing the stream wrapper so log
    records are never double-stamped."""

    def __init__(self, raw_stderr):
        super().__init__()
        self._raw = raw_stderr
        # the format worker_main.basicConfig used before this plane
        self.setFormatter(logging.Formatter(
            "[worker %(process)d] %(levelname)s %(name)s: %(message)s"))

    def emit(self, record):
        try:
            text = self.format(record)
            out = "".join(stamp_line(line, record.levelname) + "\n"
                          for line in text.split("\n"))
            self._raw.write(out)
            self._raw.flush()
        except (ValueError, OSError):
            pass  # closed stream at teardown — nowhere left to log to
        except Exception:  # noqa: BLE001 — logging must never raise
            self.handleError(record)


def install_worker_capture() -> bool:
    """Arm stdout/stderr stamping + the level-stamping root log handler
    in a WORKER process (called from worker_main before basicConfig —
    root gaining a handler here turns that basicConfig into a no-op).
    Idempotent; refuses under the kill switch."""
    if plane_disabled():
        return False
    if isinstance(sys.stdout, _StampingStream):
        return True
    raw_stderr = sys.stderr
    sys.stdout = _StampingStream(sys.stdout, "INFO")
    sys.stderr = _StampingStream(raw_stderr, "ERROR")
    root = logging.getLogger()
    root.addHandler(_StampingLogHandler(raw_stderr))
    if root.level == logging.WARNING:  # unconfigured default
        root.setLevel(logging.INFO)
    return True


# ---------------------------------------------------------------------------
# raylet-side retention: bounded per-worker rings
# ---------------------------------------------------------------------------


class LogRing:
    """Bounded per-worker line ring. Appends come from the TWO pump
    threads (stdout + stderr share one ring), reads from the raylet's
    io loop — appends serialize on a lock so ``seq`` stays strictly
    monotonic and the byte accounting exact; a read racing an append
    can at worst miss the line being appended (the follower's next
    poll gets it by seq).

    Every entry carries a monotonically increasing ``seq``, the
    follow-cursor: ``query(since_seq=s)`` returns exactly the entries a
    previous reply's cursor has not seen, across overflow drops.
    """

    def __init__(self, worker_hex: str, pid: int, maxlen: int,
                 job: Optional[str] = None):
        self.worker_hex = worker_hex
        self.pid = pid
        self.job = job
        self.alive = True
        self._ring: deque = deque(maxlen=max(16, int(maxlen)))
        self._lock = threading.Lock()
        self._seq = 0
        self._overflow_unreported = 0
        self.dropped = 0
        self.bytes = 0          # bytes currently resident in the ring
        self.lines_total = 0
        self.first_ts: Optional[float] = None
        self.last_ts: Optional[float] = None

    def append(self, stream: str, level: Optional[str], line: str,
               task: Optional[str] = None, actor: Optional[str] = None,
               job: Optional[str] = None) -> Dict[str, Any]:
        now = time.time()
        with self._lock:
            self._seq += 1
            entry = {"seq": self._seq, "ts": now, "stream": stream,
                     "level": level or ("ERROR" if stream == "stderr"
                                        else "INFO"),
                     "line": line, "task": task, "actor": actor,
                     "job": job or self.job, "pid": self.pid,
                     "worker_id": self.worker_hex}
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
                self._overflow_unreported += 1
                self.bytes -= len(self._ring[0]["line"])
            self._ring.append(entry)
            self.bytes += len(line)
            self.lines_total += 1
            if self.first_ts is None:
                self.first_ts = now
            self.last_ts = now
        return entry

    def take_overflow_delta(self) -> int:
        """Overflow drops since the last call (the pump reports them to
        the rtpu_log_dropped_lines_total{reason="ring_overflow"} series
        — exactly-once across the two pump threads via the lock)."""
        with self._lock:
            n, self._overflow_unreported = self._overflow_unreported, 0
        return n

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def next_seq(self) -> int:
        return self._seq

    def query(self, job: Optional[str] = None, task: Optional[str] = None,
              actor: Optional[str] = None, level: Optional[str] = None,
              grep: Optional[str] = None, since_seq: int = 0,
              limit: int = 10_000) -> List[Dict[str, Any]]:
        """Filtered entries with ``seq > since_seq`` (oldest first).
        ``task``/``actor`` match on hex prefix; ``level`` keeps entries
        at-or-above that severity; ``grep`` is an ``re.search`` over the
        message."""
        pattern = re.compile(grep) if grep else None
        min_rank = level_rank(level) if level else 0
        out: List[Dict[str, Any]] = []
        for entry in list(self._ring):
            if entry["seq"] <= since_seq:
                continue
            if job and entry.get("job") != job:
                continue
            if task and not (entry.get("task") or "").startswith(task):
                continue
            if actor and not (entry.get("actor") or "").startswith(actor):
                continue
            if min_rank and level_rank(entry.get("level")) < min_rank:
                continue
            if pattern is not None and not pattern.search(entry["line"]):
                continue
            out.append(entry)
            if len(out) >= limit:
                break
        return out

    def meta(self) -> Dict[str, Any]:
        return {"worker_id": self.worker_hex, "pid": self.pid,
                "job": self.job, "alive": self.alive,
                "lines": len(self._ring),
                "lines_total": self.lines_total,
                "dropped": self.dropped, "bytes": self.bytes,
                "first_ts": self.first_ts, "last_ts": self.last_ts}

    def tail(self, n: int) -> List[Dict[str, Any]]:
        if n <= 0:
            return []
        return list(self._ring)[-n:]

    def recent_tasks(self, n: int = 5) -> List[str]:
        """Most recently seen distinct task ids, newest first — the
        in-flight-task approximation for postmortems (the raylet never
        sees pushes, only the lines they emit)."""
        seen: List[str] = []
        for entry in reversed(self._ring):
            task = entry.get("task")
            if task and task not in seen:
                seen.append(task)
                if len(seen) >= n:
                    break
        return seen


class RingSet:
    """The raylet's per-worker rings: live rings keyed by worker hex,
    plus a bounded FIFO of dead workers' rings so `cli logs --task`
    still answers after the process is gone (the postmortem window)."""

    def __init__(self):
        self.live: Dict[str, LogRing] = {}
        self.dead: "OrderedDict[str, LogRing]" = OrderedDict()

    def get_or_create(self, worker_hex: str, pid: int,
                      job: Optional[str] = None) -> LogRing:
        ring = self.live.get(worker_hex)
        if ring is None:
            ring = LogRing(worker_hex, pid, CONFIG.log_ring_lines, job=job)
            self.live[worker_hex] = ring
        return ring

    def retire(self, worker_hex: str):
        ring = self.live.pop(worker_hex, None)
        if ring is None:
            return
        ring.alive = False
        self.dead[worker_hex] = ring
        while len(self.dead) > CONFIG.log_ring_dead_workers:
            self.dead.popitem(last=False)

    def all_rings(self) -> List[LogRing]:
        return list(self.live.values()) + list(self.dead.values())

    def total_bytes(self) -> int:
        return sum(r.bytes for r in self.all_rings())


# ---------------------------------------------------------------------------
# publish backpressure (the log pump's flush window)
# ---------------------------------------------------------------------------


class PublishWindow:
    """Bounds in-flight log publishes to the GCS. The pump's flush used
    to post one ``gcs.call`` per batch with NO backpressure — with the
    GCS down/slow, batches queued unboundedly on the EventLoopThread.
    Now a batch only posts while fewer than ``max_inflight`` publishes
    are outstanding; beyond the window it is DROPPED and counted, and
    the first drop of each stall logs once (rate-limited)."""

    def __init__(self, max_inflight: int):
        self.max_inflight = max(1, int(max_inflight))
        self._inflight = 0
        self._lock = threading.Lock()
        self.dropped_batches = 0
        self.dropped_lines = 0
        self._last_warn = 0.0

    def try_acquire(self, lines: int = 0) -> bool:
        with self._lock:
            if self._inflight >= self.max_inflight:
                self.dropped_batches += 1
                self.dropped_lines += lines
                now = time.monotonic()
                if now - self._last_warn > 30.0:
                    self._last_warn = now
                    logger.warning(
                        "log publish window full (%d in flight): dropping "
                        "batches (%d lines dropped so far) — GCS slow or "
                        "unreachable", self._inflight, self.dropped_lines)
                return False
            self._inflight += 1
            return True

    def release(self):
        with self._lock:
            self._inflight = max(0, self._inflight - 1)


class RateLimiter:
    """Per-worker token bucket for runaway loggers (``lines_per_s <= 0``
    disables). Gates pubsub FORWARDING only — the bounded ring always
    captures, so forensics survive a log storm that streaming drops.
    Shared by the worker's two pump threads, hence the lock."""

    def __init__(self, lines_per_s: float):
        self.rate = float(lines_per_s)
        self._allowance = self.rate
        self._last = time.monotonic()
        self._lock = threading.Lock()
        self.dropped = 0

    def allow(self, n: int = 1) -> bool:
        if self.rate <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            self._allowance = min(
                self.rate,
                self._allowance + (now - self._last) * self.rate)
            self._last = now
            if self._allowance < n:
                self.dropped += n
                return False
            self._allowance -= n
            return True


# ---------------------------------------------------------------------------
# failure forensics: exit taxonomy + postmortem reports
# ---------------------------------------------------------------------------

_SIGNAL_NAMES = {1: "SIGHUP", 2: "SIGINT", 4: "SIGILL", 6: "SIGABRT",
                 7: "SIGBUS", 8: "SIGFPE", 9: "SIGKILL", 11: "SIGSEGV",
                 13: "SIGPIPE", 15: "SIGTERM"}


def classify_exit(returncode: Optional[int],
                  last_lines: Optional[List[str]] = None,
                  kill_reason: Optional[str] = None) -> Dict[str, str]:
    """Exit-code/signal taxonomy for a dead worker process.

    ``kill_reason`` is the raylet's own annotation when IT delivered
    the kill (the memory watchdog) — a SIGKILL the raylet sent for
    memory is ``OOM_KILLED`` with certainty, while a foreign SIGKILL
    can only be flagged as *possibly* the kernel OOM killer."""
    lines = last_lines or []
    if returncode is None:
        return {"kind": "UNKNOWN", "detail": "no exit status collected"}
    if returncode < 0:
        sig = -returncode
        name = _SIGNAL_NAMES.get(sig, f"signal {sig}")
        if sig == 9:
            if kill_reason == "memory":
                return {"kind": "OOM_KILLED",
                        "detail": "SIGKILL by the node memory watchdog"}
            if kill_reason == "drain_timeout":
                return {"kind": "DRAIN_TIMEOUT_KILLED",
                        "detail": "SIGKILL by the drain deadline — the "
                                  "task outlived drain_timeout_s during "
                                  "a graceful node drain"}
            return {"kind": "SIGKILL",
                    "detail": "SIGKILL (kernel OOM killer, ray_tpu.kill,"
                              " or an external kill -9)"}
        if sig == 11:
            return {"kind": "SEGFAULT",
                    "detail": "SIGSEGV — native crash (check the stack "
                              "dump / last stderr lines)"}
        return {"kind": name, "detail": f"terminated by {name}"}
    if returncode == 0:
        return {"kind": "CLEAN_EXIT", "detail": "exit code 0"}
    if any("Traceback (most recent call last)" in line
           for line in lines):
        return {"kind": "UNCAUGHT_EXCEPTION",
                "detail": f"exit code {returncode} with a traceback in "
                          "the last captured lines"}
    return {"kind": "SYS_EXIT",
            "detail": f"exit code {returncode} (sys.exit or fatal "
                      "runtime error)"}


def build_postmortem(*, worker_hex: str, pid: int, node_id: str,
                     returncode: Optional[int], ring: Optional[LogRing],
                     kill_reason: Optional[str] = None,
                     cause: str = "") -> Dict[str, Any]:
    """Assemble one worker's postmortem: taxonomy, the ring's last N
    lines, recent task ids, and the stuck-task stack-dump file when
    the probe sweeper captured one (core_worker._probe_one writes
    /tmp/rtpu-stuck-<task8>.txt; the file survives the processes)."""
    tail_n = CONFIG.postmortem_tail_lines
    entries = ring.tail(tail_n) if ring is not None else []
    lines = [f"[{e['stream']} {e.get('level') or '?'}"
             + (f" task={e['task'][:12]}" if e.get("task") else "")
             + f"] {e['line']}" for e in entries]
    tasks = ring.recent_tasks() if ring is not None else []
    pm: Dict[str, Any] = {
        "worker_id": worker_hex,
        "pid": pid,
        "node_id": node_id,
        "ts": time.time(),
        "returncode": returncode,
        "exit": classify_exit(returncode,
                              [e["line"] for e in entries],
                              kill_reason),
        "cause": cause,
        "last_lines": lines,
        "dropped_lines": ring.dropped if ring is not None else 0,
        "tasks_recent": tasks,
    }
    for task_hex in tasks:
        path = f"/tmp/rtpu-stuck-{task_hex[:8]}.txt"
        try:
            with open(path) as f:
                pm["stack_dump"] = f.read(16384)
                pm["stack_dump_path"] = path
            break
        except OSError:
            continue
    return pm


def render_postmortem(pm: Optional[Dict[str, Any]]) -> str:
    """Human text block for embedding in driver-side exceptions."""
    if not pm:
        return ""
    exit_info = pm.get("exit") or {}
    out = [f"--- worker postmortem (pid {pm.get('pid')}, node "
           f"{(pm.get('node_id') or '?')[:12]}) ---",
           f"exit: {exit_info.get('kind', '?')} — "
           f"{exit_info.get('detail', '')}"]
    if pm.get("tasks_recent"):
        out.append("recent tasks: "
                   + ", ".join(t[:12] for t in pm["tasks_recent"]))
    lines = pm.get("last_lines") or []
    if lines:
        out.append(f"last {len(lines)} captured lines:")
        out.extend("  " + line for line in lines)
    elif plane_disabled():
        out.append("(log capture disabled: RTPU_NO_LOG_PLANE)")
    else:
        out.append("(no lines captured)")
    if pm.get("stack_dump_path"):
        out.append(f"stack dump: {pm['stack_dump_path']}")
    return "\n".join(out)


def summarize_postmortem(pm: Optional[Dict[str, Any]]) -> str:
    """One-to-three-line summary for GCS death causes (ActorDiedError
    carries this, so an actor's last words reach its callers without
    shipping the full report through every actor-info reply)."""
    if not pm:
        return ""
    exit_info = pm.get("exit") or {}
    parts = [f"exit={exit_info.get('kind', '?')}"]
    lines = pm.get("last_lines") or []
    if lines:
        parts.append("last words: " + " | ".join(
            line[-120:] for line in lines[-3:]))
    return "; ".join(parts)
