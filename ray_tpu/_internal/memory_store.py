"""In-process memory store for small objects.

Equivalent of the reference CoreWorkerMemoryStore
(src/ray/core_worker/store_provider/memory_store/): holds inlined task
results and small `put`s; `get` always consults it before the shared-memory
store. Values are stored as live Python objects (no serialization round-trip
on the in-process path). Supports both sync (user thread) and async (io loop)
waiters.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Set

from .ids import ObjectID


class _Entry:
    __slots__ = ("value", "is_exception", "in_plasma", "raw")

    def __init__(self, value: Any, is_exception: bool = False,
                 in_plasma: bool = False, raw: Optional[bytes] = None):
        self.value = value
        self.is_exception = is_exception
        # Marker entry: the real value lives in the shared-memory store.
        self.in_plasma = in_plasma
        # Lazily-deserialized payload: the reply's serialized bytes, decoded
        # on first access *by the consuming thread* — keeps deserialization
        # off the io loop and parallelizes it across getter threads.
        self.raw = raw


def resolve_entry(entry: _Entry) -> Any:
    raw = entry.raw
    if raw is not None:
        from . import serialization
        # Benign race: concurrent resolvers deserialize the same bytes and
        # assign equal values; value is set before raw is cleared.
        entry.value = serialization.deserialize(raw)
        entry.raw = None
    return entry.value


class MemoryStore:
    def __init__(self):
        self._lock = threading.Condition()
        self._objects: Dict[ObjectID, _Entry] = {}
        self._async_waiters: Dict[ObjectID, List] = {}

    def put(self, object_id: ObjectID, value: Any, is_exception: bool = False,
            in_plasma: bool = False):
        with self._lock:
            self._objects[object_id] = _Entry(value, is_exception, in_plasma)
            self._lock.notify_all()
            waiters = self._async_waiters.pop(object_id, [])
        for loop, fut in waiters:
            loop.call_soon_threadsafe(
                lambda f=fut: f.set_result(True) if not f.done() else None)

    def put_raw(self, object_id: ObjectID, data: bytes):
        """Store a still-serialized reply payload (no contained refs)."""
        with self._lock:
            self._objects[object_id] = _Entry(None, raw=data)
            self._lock.notify_all()
            waiters = self._async_waiters.pop(object_id, [])
        for loop, fut in waiters:
            loop.call_soon_threadsafe(
                lambda f=fut: f.set_result(True) if not f.done() else None)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get_entry(self, object_id: ObjectID) -> Optional[_Entry]:
        with self._lock:
            return self._objects.get(object_id)

    def wait_ready(self, object_ids: List[ObjectID], num_returns: int,
                   timeout: Optional[float]) -> Set[ObjectID]:
        """Block until `num_returns` of `object_ids` are present (or timeout).
        Returns the ready subset."""
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                ready = {o for o in object_ids if o in self._objects}
                if len(ready) >= num_returns:
                    return ready
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return ready
                self._lock.wait(remaining if remaining is not None else 1.0)

    async def wait_ready_async(self, object_id: ObjectID):
        import asyncio
        loop = asyncio.get_running_loop()
        with self._lock:
            if object_id in self._objects:
                return
            fut = loop.create_future()
            self._async_waiters.setdefault(object_id, []).append((loop, fut))
        await fut

    def delete(self, object_ids: List[ObjectID]):
        with self._lock:
            for object_id in object_ids:
                self._objects.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
