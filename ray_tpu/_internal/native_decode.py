"""Receive-path decode: the Python half of the in-ring native decoder.

src/fastrpc.cpp's epoll thread (PR 11) pre-parses the completion hot
path — flat-wire task deltas, done-stream id arrays, batched refcount
decrements — into normalized records so each shard's drain callback
consumes arrays of pre-decoded fields instead of raw frame bytes. This
module owns the Python-side record layouts (they MUST match the C
appenders byte for byte), the pack/unpack helpers for the two new raw
wire formats (``actor_tasks_done`` and ``borrow_decref_fold``), and the
kill-switch resolution.

Hot-path rules (rtpulint L006 covers this module): no per-call pickler.
The only pickling here is the done-stream's *batch* reply blob — one
``dumps_batch``/``loads_batch`` per batch of completions, annotated
``# batch ok`` — and the decoded records themselves are pure
struct/slice work feeding the ``__slots__`` TaskSpec freelists
(task_spec.spec_from_fields).

A/B: ``RTPU_NO_NATIVE_DECODE=1`` keeps every sender on the legacy wire
(pickled done streams, per-object borrow_decref RPCs) and never arms
the C decoder — the exact-legacy arm. Receivers register handlers for
BOTH forms unconditionally, so mixed-mode processes interoperate.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional, Tuple

from . import serialization
from .config import CONFIG
from .ids import TaskID

# -- record layouts (mirror src/fastrpc.cpp's appenders) --------------------

# DELTAREC fixed header: dflags, task_id, seq, attempt, method_len,
# trace0_len, trace1_len, args_len — then the four variable sections.
_REC_HEAD = struct.Struct("<B24sqIHHHI")
REC_HEAD_LEN = _REC_HEAD.size  # 47

# kind-3 decoded push_task header: msg_id, lease_id, template id,
# template announce length.
_PUSH_HEAD = struct.Struct("<QQ16sI")

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")

OBJECT_ID_LEN = 28
TASK_ID_LEN = TaskID.SIZE


def enabled() -> bool:
    """Resolved once per call site that caches it (CoreWorker init):
    native decode is on unless the kill switch says otherwise."""
    return not bool(CONFIG.no_native_decode)


# SpecFields: the pre-parsed per-call fields a DELTAREC carries, in
# task_spec.spec_from_fields argument order.
SpecFields = Tuple[bytes, int, int, Optional[str],
                   Optional[Tuple[str, str]], bytes]


def parse_delta_record(buf, off: int) -> Tuple[SpecFields, int]:
    """Parse one DELTAREC at ``buf[off:]`` -> (fields, next offset).
    ``buf`` must be bytes (records are copied out of the drain buffer
    before any await point)."""
    dflags, tid_b, seq, attempt, mlen, t0len, t1len, alen = \
        _REC_HEAD.unpack_from(buf, off)
    off += REC_HEAD_LEN
    method = None
    if mlen:
        method = buf[off:off + mlen].decode()
        off += mlen
    trace = None
    if dflags & 1:
        trace = (buf[off:off + t0len].decode(),
                 buf[off + t0len:off + t0len + t1len].decode())
        off += t0len + t1len
    args_raw = buf[off:off + alen]
    off += alen
    return (tid_b, seq, attempt, method, trace, args_raw), off


def parse_push_record(payload: bytes):
    """kind-3 record -> (msg_id, lease_id, tmpl_id, tmpl_data|None,
    SpecFields)."""
    msg_id, lease_id, tmpl_id, tlen = _PUSH_HEAD.unpack_from(payload, 0)
    off = _PUSH_HEAD.size
    tmpl_data = payload[off:off + tlen] if tlen else None
    off += tlen
    fields, _end = parse_delta_record(payload, off)
    return msg_id, lease_id, tmpl_id, tmpl_data, fields


def parse_actor_batch_record(payload: bytes):
    """kind-4 record -> (done_to, [(tid, tmpl_bytes)],
    [(tid, known, SpecFields)])."""
    (hlen,) = _U16.unpack_from(payload, 0)
    off = 2
    host = payload[off:off + hlen].decode()
    off += hlen
    (port,) = _U32.unpack_from(payload, off)
    off += 4
    n_tmpls = payload[off]
    off += 1
    tmpls = []
    for _ in range(n_tmpls):
        tid = payload[off:off + 16]
        off += 16
        (tlen,) = _U32.unpack_from(payload, off)
        off += 4
        tmpls.append((tid, payload[off:off + tlen]))
        off += tlen
    (n_recs,) = _U16.unpack_from(payload, off)
    off += 2
    recs = []
    for _ in range(n_recs):
        tid = payload[off:off + 16]
        known = payload[off + 16]
        off += 17
        (rec_len,) = _U32.unpack_from(payload, off)
        off += 4
        fields, end = parse_delta_record(payload, off)
        if end != off + rec_len:
            raise ValueError("decoded actor batch record length mismatch")
        off = end
        recs.append((tid, bool(known), fields))
    return (host, port), tmpls, recs


# -- done-stream raw wire format --------------------------------------------
# payload := u32 n | n * 24s task ids (contiguous) | batch-pickled replies

def pack_done_stream(ids: bytes, replies: List) -> bytes:
    n, rem = divmod(len(ids), TASK_ID_LEN)
    if rem:
        raise ValueError("done-stream id array not a multiple of id size")
    return (_U32.pack(n) + ids
            + serialization.dumps_batch(replies))  # batch ok: one pickle per done batch


def unpack_done_stream(payload: bytes) -> Tuple[bytes, List]:
    """-> (contiguous id bytes, replies list). The caller iterates ids
    with ids.iter_borrowed (no per-id allocation)."""
    (n,) = _U32.unpack_from(payload, 0)
    end = 4 + n * TASK_ID_LEN
    ids = payload[4:end]
    replies = serialization.loads_batch(payload[end:])  # batch ok: one unpickle per done batch
    if len(replies) != n:
        raise ValueError(
            f"done-stream id/reply count mismatch: {n} ids, "
            f"{len(replies)} replies")
    return ids, replies


# -- decref fold wire format ------------------------------------------------
# payload := k * 28-byte object ids, no framing (the C ring concatenates
# payloads across frames; any multiple of 28 is a valid fold).

def iter_fold_ids(payload) -> Iterator[bytes]:
    """Materialized object-id bytes of one fold. Unlike done-stream
    lookups these escape into the reference counter's free/notify lists,
    so they are real bytes objects, one slice per id — still one frame,
    one lock and one unpickle-free pass per BATCH of decrements."""
    if len(payload) % OBJECT_ID_LEN:
        raise ValueError("decref fold not a multiple of object-id size")
    buf = bytes(payload)
    for off in range(0, len(buf), OBJECT_ID_LEN):
        yield buf[off:off + OBJECT_ID_LEN]
