"""Node bring-up (reference: python/ray/_private/node.py + services.py).

A head node = GCS + raylet; a worker node = raylet only. In local mode both
run on the driver process's io loop (cheap, shares the in-process RPC fast
path); `cluster_utils.Cluster.add_node` runs additional raylets as
subprocesses for real multi-node semantics.
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Dict, Optional, Tuple

from .config import CONFIG
from .gcs import GcsServer
from .raylet import Raylet
from .rpc import Address, EventLoopThread
from .threads import shutdown_daemon_threads

logger = logging.getLogger(__name__)


def new_session_name() -> str:
    return f"{int(time.time())}-{uuid.uuid4().hex[:8]}"


def default_resources(num_cpus: Optional[float] = None,
                      num_tpus: Optional[float] = None) -> Dict[str, float]:
    resources: Dict[str, float] = {}
    resources["CPU"] = num_cpus if num_cpus is not None \
        else float(os.cpu_count() or 1)
    if num_tpus is None:
        from ..accelerators import tpu as tpu_accel
        num_tpus = tpu_accel.autodetect_num_chips()
    if num_tpus:
        resources["TPU"] = num_tpus
    return resources


class Node:
    """One node's processes. Head nodes own the GCS."""

    def __init__(self, head: bool, session_name: Optional[str] = None,
                 gcs_address: Optional[Address] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 node_index: int = 0,
                 object_store_memory: Optional[int] = None,
                 gcs_persist_path: Optional[str] = None,
                 gcs_port: int = 0,
                 is_head: Optional[bool] = None):
        self.head = head
        # `head` decides whether the GCS runs in-process; `is_head`
        # marks the node's ROLE in the cluster (scheduler preference,
        # serve system-actor affinity, rollout skip list). They split
        # when the GCS is a standalone killable process (external_gcs
        # clusters): the driver's co-located raylet is still the head.
        self.is_head = head if is_head is None else is_head
        self.session_name = session_name or new_session_name()
        self.node_index = node_index
        self.resources = resources or default_resources()
        self.labels = labels or {}
        self.gcs: Optional[GcsServer] = None
        self.gcs_address = gcs_address
        self.raylet: Optional[Raylet] = None
        self.object_store_memory = object_store_memory
        if gcs_persist_path is None and CONFIG.gcs_storage \
                not in ("", "memory"):
            # RTPU_GCS_STORAGE=<path> turns on durable GCS state without
            # any code change (persistence mode via RTPU_GCS_PERSIST).
            gcs_persist_path = CONFIG.gcs_storage
        self.gcs_persist_path = gcs_persist_path
        # Fixed port (head restarts keep their address, so reconnecting
        # clients need no rediscovery); 0 = ephemeral.
        self.gcs_port = gcs_port
        self.session_dir = os.path.join("/tmp", "rtpu",
                                        f"session_{self.session_name}")
        os.makedirs(self.session_dir, exist_ok=True)

    def start(self):
        loop = EventLoopThread.get()
        if self.head:
            self.gcs = GcsServer(self.session_name,
                                 persist_path=self.gcs_persist_path)
            self.gcs_address = loop.run_sync(
                self.gcs.start(port=self.gcs_port))
        assert self.gcs_address is not None
        self.raylet = Raylet(
            session_name=self.session_name,
            gcs_address=self.gcs_address,
            resources=self.resources,
            labels=self.labels,
            node_index=self.node_index,
            is_head=self.is_head,
            object_store_memory=self.object_store_memory,
            spill_dir=os.path.join(self.session_dir,
                                   f"spill-{self.node_index}"))
        loop.run_sync(self.raylet.start())
        return self

    def stop(self):
        loop = EventLoopThread.get()
        if self.raylet is not None:
            try:
                loop.run_sync(self.raylet.stop(), timeout=10)
            except Exception:
                logger.debug("raylet stop failed during node teardown",
                             exc_info=True)
        if self.gcs is not None:
            try:
                loop.run_sync(self.gcs.stop(), timeout=10)
            except Exception:
                logger.debug("gcs stop failed during node teardown",
                             exc_info=True)
        # Join registered daemon threads (metrics flusher, sweepers,
        # reapers) instead of abandoning them — bounded, best-effort.
        shutdown_daemon_threads(timeout_s=2.0)

    @property
    def node_id(self) -> str:
        return self.raylet.node_id

    @property
    def raylet_address(self) -> Address:
        return self.raylet.address
