"""ObjectRef: a first-class future/handle to an object in the cluster.

Mirrors the reference's ObjectRef semantics (python/ray/includes/object_ref):
- created by task submission (`f.remote()`), `put()`, or deserialization
- deleting the last reference releases the object (owner-side refcount;
  deserialized copies are *borrows* that decref back to the owner)
- awaitable: `await ref` resolves to the value inside async actors/drivers
- pickleable only through the framework serializer, which records the ref for
  borrower accounting (reference: "contained object ids").
"""

from __future__ import annotations

from typing import Optional, Tuple

from . import serialization
from .ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner_address", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID,
                 owner_address: Optional[Tuple[str, int]] = None,
                 _register: bool = True):
        self._id = object_id
        self._owner_address = tuple(owner_address) if owner_address else None
        self._registered = False
        if _register:
            from . import core_worker as cw
            worker = cw.try_get_core_worker()
            if worker is not None:
                worker.reference_counter.add_local_ref(self)
                self._registered = True

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def owner_address(self) -> Optional[Tuple[str, int]]:
        return self._owner_address

    def binary(self) -> bytes:
        return self._id.binary()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]})"

    def __del__(self):
        if self._registered:
            try:
                from . import core_worker as cw
                worker = cw.try_get_core_worker()
                if worker is not None:
                    worker.reference_counter.remove_local_ref(self)
            except Exception:
                pass

    def __reduce__(self):
        ctx = serialization.get_context()
        if ctx is not None:
            ctx.contained_refs.append(self)
        return (_rebuild_ref, (self._id, self._owner_address))

    def future(self):
        """concurrent.futures.Future resolving to the value."""
        from . import core_worker as cw
        return cw.get_core_worker().get_async(self)

    def __await__(self):
        import asyncio
        from . import core_worker as cw
        fut = cw.get_core_worker().get_async(self)
        return asyncio.wrap_future(fut).__await__()


class ObjectRefGenerator:
    """Handle to the refs of a generator task (reference: _raylet.pyx:297).

    `num_returns="dynamic"`: `get()` on the task's return resolves to one of
    these, holding the materialized item refs. `num_returns="streaming"`:
    `remote()` returns one directly; iteration lazily waits for the task to
    finish, then yields the item refs (item-by-item arrival streaming can
    layer in behind the same interface).
    """

    def __init__(self, refs=None, generator_ref: "ObjectRef" = None):
        self._refs = list(refs) if refs is not None else None
        self._generator_ref = generator_ref

    def _materialize(self):
        if self._refs is None:
            from . import core_worker as cw
            resolved = cw.get_core_worker().get([self._generator_ref])[0]
            self._refs = list(resolved._refs)
        return self._refs

    def __iter__(self):
        # One-shot iterator (like the reference's ObjectRefGenerator):
        # next() and for-loops share one cursor, so peeking an item then
        # looping does not re-yield it.
        return self

    def __next__(self):
        if not hasattr(self, "_iter"):
            self._iter = iter(self._materialize())
        return next(self._iter)

    def __len__(self):
        return len(self._materialize())

    def __getitem__(self, index):
        return self._materialize()[index]

    def __reduce__(self):
        return (ObjectRefGenerator, (self._refs, self._generator_ref))

    def __repr__(self):
        n = "?" if self._refs is None else len(self._refs)
        return f"ObjectRefGenerator({n} refs)"


def _rebuild_ref(object_id: ObjectID, owner_address):
    ref = ObjectRef(object_id, owner_address, _register=True)
    # A deserialized ref is a borrow: tell the owner (async, best-effort; the
    # in-flight task / containing object pins the window).
    from . import core_worker as cw
    worker = cw.try_get_core_worker()
    if worker is not None:
        worker.reference_counter.on_ref_deserialized(ref)
    return ref
