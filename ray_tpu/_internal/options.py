"""Option validation for tasks and actors.

Mirrors the reference's option surface (python/ray/_common/ray_option_utils.py)
— the full knob set users of the reference expect, normalized into TaskSpec
fields. TPU-first addition: `num_tpus` is first-class alongside `num_cpus`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..util.scheduling_strategies import (NodeAffinitySchedulingStrategy,
                                          NodeLabelSchedulingStrategy,
                                          PlacementGroupSchedulingStrategy)
from .task_spec import SchedulingStrategy

_COMMON_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "resources", "memory",
    "scheduling_strategy", "label_selector", "runtime_env", "name",
    "enable_task_events", "num_returns", "accelerator_type",
    "object_store_memory",
}
_TASK_OPTIONS = _COMMON_OPTIONS | {
    "max_retries", "retry_exceptions", "max_calls",
}
_ACTOR_OPTIONS = _COMMON_OPTIONS | {
    "max_restarts", "max_task_retries", "max_concurrency",
    "concurrency_groups", "namespace", "lifetime", "get_if_exists",
    "max_pending_calls",
}


def validate_options(options: Dict[str, Any], for_actor: bool):
    allowed = _ACTOR_OPTIONS if for_actor else _TASK_OPTIONS
    for key in options:
        if key not in allowed:
            kind = "actor" if for_actor else "task"
            raise ValueError(f"invalid option {key!r} for a {kind}")
    num_returns = options.get("num_returns")
    if num_returns is not None and not (
            isinstance(num_returns, int) and num_returns >= 0) \
            and num_returns not in ("dynamic", "streaming"):
        raise ValueError(
            "num_returns must be a non-negative int, 'dynamic' or "
            "'streaming'")
    lifetime = options.get("lifetime")
    if lifetime not in (None, "detached", "non_detached"):
        raise ValueError("lifetime must be None, 'detached' or 'non_detached'")


def resources_from_options(options: Dict[str, Any],
                           default_num_cpus: float) -> Dict[str, float]:
    resources = dict(options.get("resources") or {})
    if "CPU" in resources or "TPU" in resources or "GPU" in resources:
        raise ValueError(
            "pass CPU/GPU/TPU via num_cpus/num_gpus/num_tpus, not resources=")
    num_cpus = options.get("num_cpus")
    resources["CPU"] = default_num_cpus if num_cpus is None else num_cpus
    if options.get("num_tpus"):
        resources["TPU"] = options["num_tpus"]
    if options.get("num_gpus"):
        resources["GPU"] = options["num_gpus"]
    if options.get("memory"):
        resources["memory"] = options["memory"]
    return {k: v for k, v in resources.items() if v}


def normalize_strategy(strategy: Any) -> SchedulingStrategy:
    if strategy is None or strategy == "DEFAULT":
        return SchedulingStrategy(kind="DEFAULT")
    if strategy == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pg = strategy.placement_group
        return SchedulingStrategy(
            kind="placement_group",
            placement_group_id=pg.id,
            bundle_index=strategy.placement_group_bundle_index,
            capture_child_tasks=strategy.placement_group_capture_child_tasks)
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return SchedulingStrategy(kind="node_affinity",
                                  node_id=strategy.node_id,
                                  soft=strategy.soft)
    if isinstance(strategy, NodeLabelSchedulingStrategy):
        return SchedulingStrategy(kind="node_label",
                                  label_selector=dict(strategy.hard))
    raise ValueError(f"unsupported scheduling strategy: {strategy!r}")
