"""Owner shards: the partitioned driver-side ownership plane.

The reference closes the n:n async-call gap with a multithreaded C++
core worker whose ownership tables (reference counts, in-flight task
state) are internally partitioned. This module is the Python analog:
driver-side ownership state — lease/pending tables, the done-stream
fold, push-probe sweeps, reply routing — splits into N **owner shards**,
each owning its slice exclusively on its own io loop with its own
fastrpc ring (``NativeIO.new_ring()``), keyed by
``hash(task_id/actor_id) % N``.

Exclusivity rules:

* Loop-confined tables (submitter lease pools, actor send queues, the
  ``_awaiting`` done-stream fold, probe state) belong to exactly one
  shard and are only mutated on its loop. There are NO locks between
  shards.
* Cross-shard interactions go through a small mailbox —
  ``OwnerShard.post`` (batched ``call_soon_threadsafe``) for loop work,
  and the rpc layer's owner-loop hop for in-process calls to main-loop
  services (raylet/GCS).
* Lock-striped tables (the reference counter and pending-task slices)
  partition by id hash so unrelated ids never contend on one lock; they
  stay safe to read from any thread.

``RTPU_OWNER_SHARDS=1`` is the exact-legacy A/B path: shard 0 IS the
process-main io loop / server / client pool, no extra threads or rings
exist, and every routing function degenerates to a constant. ``0`` =
auto (min(4, cores // 2) for drivers — an io loop saturates about one
core, so small boxes stay single-loop; 1 for workers — worker-side
ownership is a nested-submission corner, not the hot path). Raylet and worker
processes are untouched; the wire format does not change.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
from typing import TYPE_CHECKING, Dict, List, Optional

from .config import CONFIG
from .rpc import Address, ClientPool, EventLoopThread, IoLoopThread, RpcServer

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .core_worker import ActorTaskSubmitter, NormalTaskSubmitter, TaskManager

logger = logging.getLogger(__name__)


def resolve_shard_count(mode: str) -> int:
    """Shard count for a CoreWorker: ``owner_shards`` flag, 0 = auto.

    Auto gives drivers min(4, cores // 2) — the submit fan-in side
    where the single-loop bottleneck lives — and workers 1 (their
    ownership tables only see nested submissions; extra loops would be
    pure thread bloat across a large worker fleet). The cores // 2
    clamp matters: an io loop saturates about one core, so sharding
    pays only when cores exceed what the submitting threads + main
    loop already use — on a 1-2 core box extra loops just fight the
    GIL (measured: the multi-client flood REGRESSES ~1.5x there,
    PERF.md round-10), so auto stays on the exact-legacy single loop
    below 4 cores."""
    n = int(CONFIG.owner_shards)
    if n > 0:
        return min(n, 64)
    if mode != "driver":
        return 1
    return max(1, min(4, (os.cpu_count() or 1) // 2))


def fire_and_forget(clients: "ClientPool", post, address: Address,
                    method: str, _retries: int = 0, **kwargs) -> None:
    """Best-effort call on whatever loop `post` targets. Pass _retries
    ONLY for IDEMPOTENT methods (return_worker: releasing a lease twice
    is a no-op) — retries re-execute on a lost reply, which would
    double-apply counter mutations like borrow_addref/decref. Shared by
    CoreWorker (main loop) and OwnerShard (shard loop) so the semantics
    can't drift apart."""
    client = clients.get(address)

    async def _go():
        try:
            await client.call(method, timeout=60, retries=_retries,
                              **kwargs)
        except Exception:
            logger.warning("fire_and_forget %s to %s dropped",
                           method, address)
    post(_go())


def route_bytes(b: bytes, n: int) -> int:
    """Deterministic id-bytes -> shard index (same id => same shard,
    stable across processes and runs: Python's salted hash() must not
    leak into routing). The first two bytes of every routable id are
    uniformly random — and ``ObjectID.for_task_return`` shares its
    task's prefix, so an object routes with the task that creates it."""
    if n <= 1:
        return 0
    return (b[0] | (b[1] << 8)) % n


class OwnerShard:
    """One shard's infrastructure: loop, ring, server, clients, and the
    per-shard ownership components CoreWorker hangs onto it. Shard 0 of
    a sharded set (and the only shard of a shards=1 set) aliases the
    process-main loop/server/pool, which makes the legacy path exact."""

    __slots__ = ("index", "tag", "is_main", "loop_thread", "server",
                 "clients", "rpc_address", "ring", "tmpl_sent",
                 "task_manager", "submitter", "actor_submitter",
                 "submit_count")

    def __init__(self, index: int):
        self.index = index
        self.tag = str(index)  # precomputed metric tag
        self.is_main = index == 0
        self.loop_thread: Optional[IoLoopThread] = None
        self.server: Optional[RpcServer] = None
        self.clients: Optional[ClientPool] = None
        self.rpc_address: Optional[Address] = None
        self.ring = None  # NativeIO ring (extra shards, native only)
        # (destination address, template id) pairs this shard has
        # announced on the flat wire path. Per shard: announces are
        # idempotent, so two shards announcing to one destination is
        # benign, while a shared set would race check-then-add across
        # loops.
        self.tmpl_sent = set()
        self.task_manager: Optional["TaskManager"] = None
        self.submitter: Optional["NormalTaskSubmitter"] = None
        self.actor_submitter: Optional["ActorTaskSubmitter"] = None
        self.submit_count = 0  # monotonic-ish; races only lose a tick

    # -- mailbox ---------------------------------------------------------

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        return self.loop_thread.loop

    def post(self, coro) -> None:
        """The cross-shard mailbox: enqueue loop work from any thread
        with batched wakeups (one self-pipe byte per burst)."""
        self.loop_thread.post(coro)

    def post_call(self, fn) -> None:
        self.loop_thread.post(fn)

    def call_soon(self, coro):
        return self.loop_thread.call_soon(coro)

    def run_sync(self, coro, timeout: Optional[float] = None):
        return self.loop_thread.run_sync(coro, timeout)

    def fire_and_forget(self, address: Address, method: str,
                        _retries: int = 0, **kwargs):
        """Best-effort call on THIS shard's loop/clients (the shard-local
        analog of CoreWorker.fire_and_forget; same idempotency caveat on
        _retries)."""
        fire_and_forget(self.clients, self.post, address, method,
                        _retries=_retries, **kwargs)

    # -- observability ---------------------------------------------------

    def queue_depth(self) -> int:
        """Outstanding owned work on this shard: tasks pushed/awaiting
        replies plus queued lease waiters plus undrained mailbox posts.
        Racy len() snapshots — observability only, never control flow."""
        depth = 0
        sub = self.submitter
        if sub is not None:
            depth += len(sub._running)  # cross-shard ok: racy observability snapshot
            waiters = sub._waiters  # cross-shard ok: racy observability snapshot
            depth += sum(len(q) for q in list(waiters.values()))
        asub = self.actor_submitter
        if asub is not None:
            depth += len(asub._awaiting)  # cross-shard ok: racy observability snapshot
        if self.loop_thread is not None:
            depth += self.loop_thread.pending_posts()
        return depth


class ShardSet:
    """The N owner shards of one CoreWorker plus routing and teardown.

    Construction is thread-free; ``start_main``/``start_extra`` bring the
    loops/rings/servers up inside CoreWorker.start(), and ``stop()``
    tears every extra loop down (the threads.py registry joins them as a
    backstop at node teardown)."""

    def __init__(self, count: int):
        self.count = max(1, count)
        self.shards: List[OwnerShard] = [OwnerShard(i)
                                         for i in range(self.count)]
        self._started = False
        self._lag_lock = threading.Lock()
        self._lag_s: Dict[int, float] = {}

    def __iter__(self):
        return iter(self.shards)

    def __len__(self):
        return self.count

    @property
    def main(self) -> OwnerShard:
        return self.shards[0]

    # -- routing ---------------------------------------------------------

    def for_task(self, task_id) -> OwnerShard:
        return self.shards[route_bytes(task_id.binary(), self.count)]

    def for_actor(self, actor_id) -> OwnerShard:
        return self.shards[route_bytes(actor_id.binary(), self.count)]

    def for_spec(self, spec) -> OwnerShard:
        from .task_spec import ACTOR_TASK
        if spec.task_type == ACTOR_TASK:
            return self.for_actor(spec.actor_id)
        return self.for_task(spec.task_id)

    # -- lifecycle -------------------------------------------------------

    def start_main(self, main_loop_thread, server: RpcServer,
                   clients: ClientPool, rpc_address: Address):
        """Bind shard 0 to the process-main loop/server/pool (already
        started by CoreWorker.start)."""
        shard = self.shards[0]
        shard.loop_thread = main_loop_thread
        shard.server = server
        shard.clients = clients
        shard.rpc_address = rpc_address

    def start_extra(self, name_prefix: str):
        """Spawn loops + rings + reply servers for shards 1..N-1."""
        if self._started or self.count == 1:
            self._started = True
            return
        from .rpc import _native_io
        native = _native_io() is not None
        for shard in self.shards[1:]:
            shard.loop_thread = IoLoopThread(
                name=f"rtpu-owner-shard-{shard.index}", joinable=True)
            if native:
                from .._native.fastrpc import NativeIO
                shard.ring = NativeIO.new_ring()
                if shard.ring is None:
                    logger.warning(
                        "owner shard %d: no native ring available; "
                        "falling back to the asyncio transport",
                        shard.index)
            # nio=False forces the asyncio transport when this shard has
            # no ring of its own while the process ring exists — falling
            # through to ring 0 would drain this shard's frames on the
            # MAIN loop.
            nio = shard.ring if shard.ring is not None \
                else (False if native else None)
            shard.server = RpcServer(
                f"{name_prefix}-shard{shard.index}", nio=nio)
            shard.clients = ClientPool(nio=nio,
                                       loop_thread=shard.loop_thread)
            shard.rpc_address = shard.run_sync(shard.server.start())
        self._started = True

    def stop(self, timeout_s: float = 5.0):
        """Tear down extra shards: reply servers, cached clients, loops,
        rings (recycled into the process pool for the next init)."""
        for shard in self.shards[1:]:
            if shard.loop_thread is None:
                continue
            if shard.server is not None:
                try:
                    shard.run_sync(shard.server.stop(), timeout=timeout_s)
                except Exception:
                    logger.debug("shard %d server stop failed",
                                 shard.index, exc_info=True)
            if shard.clients is not None:
                try:
                    shard.clients.close_all()
                except Exception:
                    logger.debug("shard %d client close failed",
                                 shard.index, exc_info=True)
            if shard.ring is not None:
                try:
                    shard.run_sync(_detach_ring(shard.ring, shard.loop),
                                   timeout=2.0)
                except Exception:
                    logger.debug("shard %d ring detach failed",
                                 shard.index, exc_info=True)
            shard.loop_thread.join(timeout=timeout_s)
            if shard.ring is not None:
                from .._native.fastrpc import NativeIO
                NativeIO.release_ring(shard.ring)
                shard.ring = None

    # -- observability ---------------------------------------------------

    def refresh_gauges(self) -> Dict[int, int]:
        """Update the per-shard gauges and kick async loop-lag probes
        (sampled on demand — cli status / dashboard / memory report —
        so an idle cluster pays nothing). Returns the sampled queue
        depths so stats() reuses the same walk (and its rows agree
        with the gauges within one sample)."""
        from .runtime_metrics import runtime_metrics
        metrics = runtime_metrics()
        pid = str(os.getpid())
        depths: Dict[int, int] = {}
        for shard in self.shards:
            depths[shard.index] = depth = shard.queue_depth()
            metrics.shard_queue_depth.set(
                depth, tags={"pid": pid, "shard": shard.tag})
            lag = self._lag_s.get(shard.index)
            if lag is not None:
                metrics.shard_loop_lag.set(
                    lag, tags={"pid": pid, "shard": shard.tag})
            if shard.loop_thread is None:
                continue
            t0 = time.monotonic()

            def _probe(shard=shard, t0=t0):
                dt = time.monotonic() - t0
                with self._lag_lock:
                    self._lag_s[shard.index] = dt
                metrics.shard_loop_lag.set(
                    dt, tags={"pid": pid, "shard": shard.tag})
            try:
                shard.loop.call_soon_threadsafe(_probe)
            except RuntimeError:
                logger.debug("lag probe on stopped shard loop skipped",
                             exc_info=True)
        return depths

    def stats(self) -> List[Dict[str, object]]:
        """Per-shard rows for cli status / the dashboard node view."""
        depths = self.refresh_gauges()
        rows = []
        for shard in self.shards:
            rows.append({
                "shard": shard.index,
                "queue_depth": depths.get(shard.index, 0),
                "submits": shard.submit_count,
                "loop_lag_s": self._lag_s.get(shard.index),
                "rpc_address": list(shard.rpc_address)
                if shard.rpc_address else None,
                "native_ring": shard.ring._ring
                if shard.ring is not None else (0 if shard.is_main
                                                else None),
            })
        return rows


async def _detach_ring(ring, loop):
    ring.detach(loop)
