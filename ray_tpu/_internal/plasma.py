"""Shared-memory object store (plasma equivalent).

Role of the reference's plasma store (src/ray/object_manager/plasma/):
node-local shared memory holding large immutable objects, zero-copy readable
by every worker on the node, with LRU eviction and spill-to-disk overflow.

TPU-first design decisions (vs the reference's single store daemon owning one
dlmalloc arena with fd-passing over a unix socket):

- Objects are individual files in a per-session tmpfs directory
  (`/dev/shm/rtpu-<session>/`). The *producer* maps and writes the object
  directly — creation never crosses a process boundary; only the cheap `seal`
  notification goes to the raylet. Readers `mmap` the file read-only; numpy /
  jax host arrays deserialize as views over the mapping (pickle-5 out-of-band
  buffers), so `get` of a 100 GiB array is O(pages touched), not O(copy).
- Eviction unlinks the file. Linux keeps the pages alive for processes that
  still hold the mapping, which gives us plasma's "evicted while borrowed is
  safe" behavior without refcounted fd passing.
- The raylet owns accounting (capacity, LRU clock, pin counts, spill) in
  `LocalObjectManager`; this module is just the mechanical shm layer that any
  process can use.

An optional C++ arena allocator (native/) can back small-object slabs; files
are the general path.
"""

from __future__ import annotations

import logging
import mmap
import os
import shutil
import threading
from typing import Dict, Optional

from .ids import ObjectID
from . import serialization

logger = logging.getLogger(__name__)


def shm_root() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"


# Objects up to this size go through the C++ arena (one lock + memcpy, no
# syscalls); larger ones are individual files (mmap views, spillable).
ARENA_OBJECT_LIMIT = 256 * 1024
ARENA_CAPACITY = 256 * 1024 * 1024


class PlasmaDir:
    """Mechanical access to one node's object directory in shm."""

    def __init__(self, session_name: str, node_index: int = 0):
        self.path = os.path.join(shm_root(), f"rtpu-{session_name}-{node_index}")
        os.makedirs(self.path, exist_ok=True)
        self._lock = threading.Lock()
        # Keep created-but-unsealed mmaps so the producer can write then seal.
        self._creating: Dict[ObjectID, mmap.mmap] = {}
        self._arena = self._attach_arena()

    def _attach_arena(self):
        """Shared C++ arena for small objects (reference: the plasma
        dlmalloc arena, N9). First process to win the lock file
        initializes; everyone else attaches. Failure -> files only."""
        try:
            from .._native.shm_store import ArenaStore
        except Exception:  # noqa: BLE001 — optional native path
            return None
        arena_path = os.path.join(self.path, "arena")
        try:
            try:
                fd = os.open(arena_path + ".lock",
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                creator = True
            except FileExistsError:
                creator = False
            if creator:
                # Build fully at a private path, then publish atomically:
                # attachers must never observe a zero-length/uninitialized
                # segment (mmap of an empty file raises and would silently
                # degrade that process to files-only, splitting the node's
                # view of small objects).
                tmp = arena_path + f".init-{os.getpid()}"
                store = ArenaStore(tmp, ARENA_CAPACITY, create=True)
                os.rename(tmp, arena_path)
                store.path = arena_path
                return store
            import time
            deadline = time.monotonic() + 120  # creator may be compiling
            while not os.path.exists(arena_path):
                if time.monotonic() > deadline:
                    return None
                time.sleep(0.01)
            return ArenaStore(arena_path, 0, create=False)
        except Exception:  # noqa: BLE001 — toolchain/init failure
            return None

    def _akey(self, object_id: ObjectID) -> bytes:
        import hashlib
        return hashlib.sha1(object_id.binary()).digest()

    def _file(self, object_id: ObjectID) -> str:
        return os.path.join(self.path, object_id.hex())

    # -- producer path ----------------------------------------------------

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        path = self._file(object_id) + ".tmp"
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, size)
            m = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        with self._lock:
            self._creating[object_id] = m
        return memoryview(m)

    def seal(self, object_id: ObjectID) -> int:
        """Make the object visible to readers; returns its size."""
        with self._lock:
            m = self._creating.pop(object_id, None)
        path = self._file(object_id)
        os.rename(path + ".tmp", path)
        size = os.path.getsize(path)
        if m is not None:
            m.close()
        return size

    def put_serialized(self, object_id: ObjectID,
                       obj: serialization.SerializedObject) -> int:
        """Write header + pickle + out-of-band buffers with one writev.

        Faster than memcpy into a fresh mmap (which page-faults every 4K
        on first touch): the kernel streams into the page cache at memory
        bandwidth. Readers still mmap the sealed file for zero-copy views.
        """
        total_bytes = obj.total_bytes()
        if self._arena is not None and total_bytes <= ARENA_OBJECT_LIMIT:
            from .._native.shm_store import ArenaStoreError
            key = self._akey(object_id)
            try:
                buf = self._arena.create(key, total_bytes)
            except ArenaStoreError:
                buf = None  # full/exists: fall through to the file path
            if buf is not None:
                try:
                    obj.write_into(buf)
                    buf.release()
                    self._arena.seal(key)
                except BaseException:
                    # Never leak an unsealed (unevictable) entry.
                    try:
                        buf.release()
                    except Exception:  # noqa: BLE001 — already released
                        logger.debug("buffer release during seal-failure "
                                     "cleanup raised", exc_info=True)
                    self._arena.delete(key)
                    raise
                return total_bytes
        import struct as _struct
        path = self._file(object_id) + ".tmp"
        fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o600)
        try:
            header = bytearray(9 + 8 * len(obj.buffers))
            _struct.pack_into(">BII", header, 0, 1, len(obj.pickle_bytes),
                              len(obj.buffers))
            off = 9
            for b in obj.buffers:
                _struct.pack_into(">Q", header, off, b.nbytes)
                off += 8
            parts = [bytes(header), obj.pickle_bytes]
            for b in obj.buffers:
                parts.append(b.cast("B") if b.ndim == 1
                             else memoryview(bytes(b)))
            total = sum(len(p) if isinstance(p, bytes) else p.nbytes
                        for p in parts)
            written = 0
            while parts:
                # IOV_MAX (1024) bounds a single writev; large pytrees
                # serialize to thousands of out-of-band buffers.
                wrote = os.writev(fd, parts[:1024])
                written += wrote
                while parts and wrote >= (len(parts[0])
                                          if isinstance(parts[0], bytes)
                                          else parts[0].nbytes):
                    first = parts.pop(0)
                    wrote -= (len(first) if isinstance(first, bytes)
                              else first.nbytes)
                if wrote and parts:
                    head = parts[0]
                    head = memoryview(head) if isinstance(head, bytes) \
                        else head
                    parts[0] = head[wrote:]
            assert written == total, (written, total)
        finally:
            os.close(fd)
        os.rename(path, self._file(object_id))
        return total

    def abort(self, object_id: ObjectID):
        with self._lock:
            m = self._creating.pop(object_id, None)
        if m is not None:
            m.close()
        try:
            os.unlink(self._file(object_id) + ".tmp")
        except FileNotFoundError:
            pass

    # -- reader path ------------------------------------------------------

    def contains(self, object_id: ObjectID) -> bool:
        if os.path.exists(self._file(object_id)):
            return True
        return self._arena is not None and \
            self._arena.contains(self._akey(object_id))

    def _arena_read(self, object_id: ObjectID) -> Optional[bytes]:
        """Copy a small object out of the arena (and unpin). Small objects
        are copied rather than viewed so the pin can be dropped
        immediately — zero-copy stays the contract for large (file)
        objects only."""
        if self._arena is None:
            return None
        key = self._akey(object_id)
        view = self._arena.get(key)
        if view is None:
            return None
        try:
            return bytes(view)
        finally:
            view.release()
            self._arena.release(key)

    def map_read(self, object_id: ObjectID) -> Optional[memoryview]:
        """Zero-copy read-only view; None if absent."""
        try:
            fd = os.open(self._file(object_id), os.O_RDONLY)
        except FileNotFoundError:
            data = self._arena_read(object_id)
            return memoryview(data) if data is not None else None
        try:
            size = os.fstat(fd).st_size
            m = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        return memoryview(m)

    def get(self, object_id: ObjectID):
        view = self.map_read(object_id)
        if view is None:
            return None, False
        return serialization.deserialize_from_buffer(view), True

    def read_bytes(self, object_id: ObjectID) -> Optional[bytes]:
        view = self.map_read(object_id)
        if view is None:
            return None
        try:
            return bytes(view)
        finally:
            view.release()

    def write_bytes(self, object_id: ObjectID, data: bytes) -> int:
        buf = self.create(object_id, len(data))
        buf[:] = data
        buf.release()
        return self.seal(object_id)

    # -- management (raylet-only) ----------------------------------------

    def delete(self, object_id: ObjectID):
        try:
            os.unlink(self._file(object_id))
        except FileNotFoundError:
            if self._arena is not None:
                self._arena.delete(self._akey(object_id))

    def size_of(self, object_id: ObjectID) -> int:
        try:
            return os.path.getsize(self._file(object_id))
        except FileNotFoundError:
            if self._arena is not None:
                # Native size lookup: the old path copied the whole
                # object out of the arena just to take len() of it.
                size = self._arena.size_of(self._akey(object_id))
                if size is not None:
                    return size
            raise

    def spill_to(self, object_id: ObjectID, spill_dir: str) -> str:
        """Move object to disk; returns the spilled path."""
        os.makedirs(spill_dir, exist_ok=True)
        dest = os.path.join(spill_dir, object_id.hex())
        file_path = self._file(object_id)
        if os.path.exists(file_path):
            shutil.move(file_path, dest)
        else:
            data = self._arena_read(object_id)
            if data is None:
                raise FileNotFoundError(file_path)
            with open(dest, "wb") as f:
                f.write(data)
            self._arena.delete(self._akey(object_id))
        return dest

    def restore_from(self, object_id: ObjectID, spilled_path: str):
        shutil.move(spilled_path, self._file(object_id))

    def destroy(self):
        if self._arena is not None:
            self._arena.close()
            self._arena = None
        shutil.rmtree(self.path, ignore_errors=True)
