"""Continuous stack-sampling profiler: the CPU leg of the
observability triad (PR 1 time, PR 3 memory, this module CPU).

Every process (worker, raylet, GCS, driver) can run one `StackSampler`
— a registry-registered daemon thread that samples
``sys._current_frames()`` at a configurable rate into a bounded ring.
Each sample is tagged with the task/actor-method the sampled thread is
executing (the `TaskExecutor` notes its current spec in
:data:`_CURRENT_TASKS` around user code), so folded profiles attribute
CPU to tasks and actor classes, not just frames — the py-spy analog
with no subprocess and no ptrace, per the Parca/conprof
"always-cheap sampling, post-hoc aggregation" design (PAPERS.md).

Capture flow: CoreWorker/Raylet/GCS expose ``start_profiling`` /
``stop_profiling`` / ``get_profile`` RPCs over this module's process
singleton; the raylet fans a node capture out to all its workers, and
``util/state.profile_cluster`` merges node reports into one collapsed
flamegraph + speedscope document + top-N attribution tables.

Processes sharing one OS process (local-mode driver + raylet + GCS)
share the singleton: ``start_profiling`` is idempotent (the first
caller owns the stop) and ``get_profile(clear=True)`` *drains* the
ring, so concurrent collectors split samples instead of double-counting
them.

Kill switch: ``RTPU_NO_PROFILER=1`` — ``start_profiling`` refuses and
no thread is ever spawned (off-mode cost is zero).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback
from collections import Counter, deque
from typing import Any, Dict, List, Optional, Tuple

from .config import CONFIG

logger = logging.getLogger(__name__)

# Executor threads: samples on these threads with no task attribution
# are "idle executor" time (the running-vs-idle split in
# profile_cluster reports).
_EXECUTOR_THREAD_PREFIXES = ("rtpu-exec", "rtpu-actor", "rtpu-cg-")

# thread ident -> TaskSpec currently executing user code there. Written
# by TaskExecutor around every task body (two dict ops per task — cheap
# enough to stay on even with the profiler off, and it doubles as
# attribution for fleet stack dumps). The sampler reads it racily: a
# spec recycled between read and attribute access can at worst
# mis-attribute one sample, which a sampling profiler tolerates.
_CURRENT_TASKS: Dict[int, Any] = {}


def note_task(spec) -> None:
    """Mark `spec` as executing on the calling thread (executor hook)."""
    _CURRENT_TASKS[threading.get_ident()] = spec


def clear_task() -> None:
    _CURRENT_TASKS.pop(threading.get_ident(), None)


def _task_key(spec) -> Optional[Tuple[str, str, str]]:
    """(task_hex, display name, actor class) for one executing spec."""
    if spec is None:
        return None
    try:
        name = spec.name or spec.method_name \
            or spec.function.display_name()
        actor = spec.function.qualname if spec.actor_id is not None else ""
        return (spec.task_id.hex(), name, actor)
    except Exception:  # noqa: BLE001 — racing a freelist recycle
        return None


# ---------------------------------------------------------------------------
# the sampler
# ---------------------------------------------------------------------------


class StackSampler:
    """Daemon-thread sampler over ``sys._current_frames()``.

    Samples land in a bounded ring (`deque(maxlen=ring_size)`) as
    ``(thread_name, task_key, stack)`` tuples with the stack root-first;
    `snapshot()` folds them into aggregated rows. Overflow drops the
    OLDEST sample (the ring is a window onto the recent past) and
    counts it in `dropped`.
    """

    def __init__(self, hz: float, ring_size: int):
        self.hz = max(1.0, min(float(hz), 1000.0))
        self.interval = 1.0 / self.hz
        self.ring_size = max(16, int(ring_size))
        self._ring: deque = deque(maxlen=self.ring_size)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_total = 0
        self.dropped = 0
        self.started_at = time.time()
        # f_code -> "name (basename" render prefix; code objects are
        # interned for the process lifetime so the cache is bounded by
        # the amount of loaded code.
        self._code_cache: Dict[Any, str] = {}

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._stop.is_set()

    def start(self):
        from .threads import spawn_daemon
        self._thread = spawn_daemon(
            self._loop, name=f"rtpu-profiler-{os.getpid()}",
            stop=self._stop.set)
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        from .runtime_metrics import runtime_metrics
        metrics = runtime_metrics()
        tags = {"pid": str(os.getpid())}
        while not self._stop.wait(self.interval):
            t0 = time.perf_counter()
            dropped_before = self.dropped
            try:
                n = self._sample_once()
            except Exception:  # noqa: BLE001 — sampler must survive
                logger.debug("profiler sampling pass failed",
                             exc_info=True)
                continue
            metrics.profiler_samples.inc(n, tags=tags)
            if self.dropped > dropped_before:
                metrics.profiler_dropped.inc(
                    self.dropped - dropped_before, tags=tags)
            metrics.profiler_pass_seconds.observe(
                time.perf_counter() - t0, tags=tags)

    def _sample_once(self) -> int:
        """One pass over every live thread; returns samples recorded."""
        own = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        ring = self._ring
        cache = self._code_cache
        n = 0
        for ident, frame in sys._current_frames().items():
            if ident == own:
                continue
            stack: List[str] = []
            f = frame
            depth = 0
            while f is not None and depth < 128:
                code = f.f_code
                prefix = cache.get(code)
                if prefix is None:
                    prefix = (f"{code.co_name} "
                              f"({os.path.basename(code.co_filename)}")
                    cache[code] = prefix
                stack.append(f"{prefix}:{f.f_lineno})")
                f = f.f_back
                depth += 1
            stack.reverse()  # root-first, the collapsed-stack order
            task = _task_key(_CURRENT_TASKS.get(ident))
            if len(ring) == ring.maxlen:
                self.dropped += 1
            ring.append((names.get(ident, str(ident)), task,
                         tuple(stack)))
            n += 1
        self.samples_total += n
        return n

    def snapshot(self, clear: bool = False) -> List[Dict[str, Any]]:
        """Fold the ring into aggregated rows. ``clear=True`` DRAINS the
        ring sample-by-sample, so two concurrent collectors in a shared
        process split the samples instead of double-counting them."""
        if clear:
            samples = []
            ring = self._ring
            while True:
                try:
                    samples.append(ring.popleft())
                except IndexError:
                    break
        else:
            samples = list(self._ring)
        return fold_samples(samples)

    def status(self) -> Dict[str, Any]:
        return {
            "pid": os.getpid(),
            "running": self.running,
            "hz": self.hz,
            "ring_size": self.ring_size,
            "ring_len": len(self._ring),
            "samples_total": self.samples_total,
            "dropped": self.dropped,
            "started_at": self.started_at,
        }


def fold_samples(samples) -> List[Dict[str, Any]]:
    """Fold raw (thread, task, stack) samples into count rows."""
    counts: Counter = Counter()
    for thread, task, stack in samples:
        counts[(thread, task, stack)] += 1
    rows = []
    for (thread, task, stack), count in counts.items():
        rows.append({
            "thread": thread,
            "task": task[0] if task else None,
            "task_name": task[1] if task else None,
            "actor": (task[2] or None) if task else None,
            "stack": list(stack),
            "count": count,
        })
    rows.sort(key=lambda r: -r["count"])
    return rows


# ---------------------------------------------------------------------------
# process singleton (RPC backend)
# ---------------------------------------------------------------------------

_SAMPLER: Optional[StackSampler] = None
_SAMPLER_LOCK = threading.Lock()


def profiler_disabled() -> bool:
    return bool(CONFIG.no_profiler)


def start_profiling(hz: Optional[float] = None,
                    ring_size: Optional[int] = None) -> Dict[str, Any]:
    """Start (or join) this process's sampler. Returns
    ``already_running`` so the starter that actually spawned the thread
    knows it owns the stop."""
    if profiler_disabled():
        return {"running": False, "already_running": False,
                "error": "profiler disabled (RTPU_NO_PROFILER)"}
    global _SAMPLER
    with _SAMPLER_LOCK:
        sampler = _SAMPLER
        if sampler is not None and sampler.running:
            return {"running": True, "already_running": True,
                    "hz": sampler.hz, "pid": os.getpid()}
        sampler = StackSampler(
            hz if hz else CONFIG.profiler_hz,
            ring_size if ring_size else CONFIG.profiler_ring_size)
        sampler.start()
        _SAMPLER = sampler
    return {"running": True, "already_running": False,
            "hz": sampler.hz, "pid": os.getpid()}


def stop_profiling() -> bool:
    sampler = _SAMPLER
    if sampler is None:
        return False
    sampler.stop()
    return True


def get_profile(clear: bool = True, stop: bool = False) -> Dict[str, Any]:
    """This process's folded profile + identity/meta. The ring survives
    a stop, so collect-after-stop orderings lose nothing."""
    sampler = _SAMPLER
    if sampler is None:
        return {"pid": os.getpid(), "samples": [], "meta": {
            "running": False, "samples_total": 0, "dropped": 0}}
    if stop:
        sampler.stop()
    return {"pid": os.getpid(),
            "samples": sampler.snapshot(clear=clear),
            "meta": sampler.status()}


def profiling_status() -> Dict[str, Any]:
    sampler = _SAMPLER
    if sampler is None:
        return {"pid": os.getpid(), "running": False,
                "disabled": profiler_disabled()}
    return dict(sampler.status(), disabled=profiler_disabled())


def maybe_autostart() -> bool:
    """Continuous mode: every process starts sampling at boot when
    ``profiler_autostart_hz`` > 0 (off by default; the kill switch wins
    over it)."""
    hz = CONFIG.profiler_autostart_hz
    if hz <= 0 or profiler_disabled():
        return False
    return bool(start_profiling(hz).get("running"))


# ---------------------------------------------------------------------------
# whole-process stack dump (cli stack / handle_dump_stacks backend)
# ---------------------------------------------------------------------------


def stack_dump_text(asyncio_tasks=None) -> str:
    """Render every thread's full stack (and, when the caller passes
    ``asyncio.all_tasks()``, every asyncio task's UNTRUNCATED stack) as
    text, with task attribution for executor threads."""
    lines: List[str] = [f"=== pid {os.getpid()} stack dump "
                        f"({time.strftime('%H:%M:%S')}) ==="]
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, "?")
        running = _task_key(_CURRENT_TASKS.get(ident))
        tag = (f"  [task {running[1]} {running[0][:12]}]"
               if running else "")
        lines.append(f"\nThread {name} (ident {ident}){tag}:")
        lines.append("".join(traceback.format_stack(frame)).rstrip())
    if asyncio_tasks:
        lines.append("\n--- asyncio tasks ---")
        for t in asyncio_tasks:
            try:
                frames = t.get_stack()
                where = " <- ".join(
                    f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:"
                    f"{f.f_code.co_name}:{f.f_lineno}"
                    for f in frames) or "(no frames)"
                lines.append(f"TASK {t.get_coro().__qualname__} @ {where}")
            except Exception:  # noqa: BLE001 — task may complete mid-walk
                logger.debug("asyncio task stack render failed",
                             exc_info=True)
    lines.append("")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# report rendering (shared by util/state.profile_cluster and tests)
# ---------------------------------------------------------------------------


def collapse_rows(rows: List[Dict[str, Any]]) -> str:
    """Collapsed-stack text ("frame;frame;frame count" per line, the
    flamegraph.pl / speedscope-import format). Task-attributed stacks
    get a synthetic root frame naming the task so attribution survives
    into the flamegraph itself."""
    counts: Counter = Counter()
    for row in rows:
        stack = list(row["stack"])
        if row.get("task_name"):
            stack.insert(0, f"task:{row['task_name']}")
        counts[";".join(stack)] += row["count"]
    return "\n".join(
        f"{stack} {count}"
        for stack, count in sorted(counts.items(),
                                   key=lambda kv: (-kv[1], kv[0])))


def speedscope_document(rows: List[Dict[str, Any]],
                        name: str = "rtpu profile",
                        hz: float = 100.0) -> Dict[str, Any]:
    """speedscope.app "sampled" profile: shared frame table + one
    weighted sample per folded row (weight = sample count / the row's
    sampling rate → seconds; ``row["hz"]`` overrides the profile-wide
    `hz` for processes sampled at a different rate)."""
    frame_index: Dict[str, int] = {}
    frames: List[Dict[str, str]] = []
    samples: List[List[int]] = []
    weights: List[float] = []
    for row in rows:
        stack = list(row["stack"])
        if row.get("task_name"):
            stack.insert(0, f"task:{row['task_name']}")
        indexed = []
        for frame in stack:
            idx = frame_index.get(frame)
            if idx is None:
                idx = frame_index[frame] = len(frames)
                frames.append({"name": frame})
            indexed.append(idx)
        samples.append(indexed)
        weights.append(row["count"] / (row.get("hz") or hz))
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "seconds",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
        "exporter": "ray_tpu",
        "name": name,
    }


def top_attribution(rows: List[Dict[str, Any]], hz: float,
                    top: int = 20) -> Dict[str, List[Dict[str, Any]]]:
    """Top-N CPU attribution tables: by task, by actor class, and by
    (self/leaf) frame. ``cpu_s`` is exclusive sampled CPU time — each
    row converts at its own sampling rate (``row["hz"]``, set by the
    cluster merge when a process's continuous sampler runs at a
    different rate than the capture asked for), falling back to the
    capture-wide `hz`."""
    by_task: Dict[Tuple, Dict[str, Any]] = {}
    by_actor: Dict[str, Dict[str, Any]] = {}
    by_frame: Dict[str, Dict[str, Any]] = {}
    for row in rows:
        count = row["count"]
        secs = count / (row.get("hz") or hz)
        if row.get("task"):
            agg = by_task.setdefault(
                (row["task"], row.get("task_name")),
                {"task": row["task"], "name": row.get("task_name"),
                 "actor": row.get("actor"), "samples": 0, "cpu_s": 0.0})
            agg["samples"] += count
            agg["cpu_s"] += secs
        if row.get("actor"):
            agg = by_actor.setdefault(
                row["actor"],
                {"actor": row["actor"], "samples": 0, "cpu_s": 0.0})
            agg["samples"] += count
            agg["cpu_s"] += secs
        if row["stack"]:
            leaf = row["stack"][-1]
            agg = by_frame.setdefault(
                leaf, {"frame": leaf, "samples": 0, "cpu_s": 0.0})
            agg["samples"] += count
            agg["cpu_s"] += secs

    def _top(table):
        out = sorted(table.values(), key=lambda a: -a["cpu_s"])[:top]
        for agg in out:
            agg["cpu_s"] = round(agg["cpu_s"], 3)
        return out

    return {"by_task": _top(by_task), "by_actor": _top(by_actor),
            "by_frame": _top(by_frame)}


def executor_split(rows: List[Dict[str, Any]]) -> Dict[str, int]:
    """Running-vs-idle split for executor threads: a sample on an
    executor thread with no task attribution is idle executor time."""
    running = idle = 0
    for row in rows:
        thread = row.get("thread") or ""
        if not thread.startswith(_EXECUTOR_THREAD_PREFIXES):
            continue
        if row.get("task"):
            running += row["count"]
        else:
            idle += row["count"]
    return {"running": running, "idle": idle}
