"""Raylet: the per-node scheduling and data plane.

Equivalent of the reference raylet (src/ray/raylet/): worker-lease
scheduling with spillback, a worker pool of language workers, placement-group
bundle accounting with two-phase prepare/commit, the local object manager
(eviction, spill/restore, remote pulls via chunked transfer — the role of
plasma's PullManager/PushManager over object_manager.proto), node heartbeats
carrying the resource view, and worker liveness supervision.

One raylet per node. In local mode it runs inside the driver process on the
shared io loop; `cluster_utils.Cluster.add_node` runs additional raylets as
subprocesses for multi-node semantics on one machine (reference:
python/ray/cluster_utils.py).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import random
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from . import aio
from .backoff import Backoff
from .config import CONFIG
from .ids import NodeID, ObjectID, PlacementGroupID, WorkerID
from . import logplane
from .memory_store import MemoryStore
from .plasma import PlasmaDir
from .resources import NodeResources, ResourceSet
from .rpc import Address, ClientPool, RpcServer
from .scheduling_policy import NodeView
from . import scheduling_policy

logger = logging.getLogger(__name__)

HEARTBEAT_INTERVAL_S = 0.2


@dataclass
class WorkerHandle:
    worker_id: bytes
    address: Optional[Address] = None
    pid: int = 0
    proc: Optional[subprocess.Popen] = None
    state: str = "STARTING"         # STARTING | IDLE | LEASED | DEAD
    env_key: Tuple = ()
    lease_id: Optional[int] = None
    registered: Optional[asyncio.Future] = None
    last_idle: float = 0.0
    is_actor_worker: bool = False
    job_hex: Optional[str] = None  # last-leased job (log-stream routing)
    # Set when the RAYLET delivered the kill (memory watchdog): the
    # postmortem taxonomy then reports OOM_KILLED with certainty
    # instead of guessing at a foreign SIGKILL.
    kill_reason: Optional[str] = None


@dataclass
class LeaseRequest:
    lease_id: int
    demand: ResourceSet
    spec_meta: Dict[str, Any]
    future: asyncio.Future = None
    pg: Optional[Tuple[PlacementGroupID, int]] = None
    # Queue-age accounting (autoscaler scale-up signal + the
    # rtpu_lease_queue_age_seconds gauge): when this request arrived.
    enqueued_at: float = 0.0


@dataclass
class BundleAccount:
    resources: ResourceSet
    available: ResourceSet
    committed: bool = False


@dataclass
class ObjectEntry:
    size: int
    last_access: float
    pinned: int = 0
    spilled_path: Optional[str] = None


class Raylet:
    def __init__(self, session_name: str, gcs_address: Address,
                 resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 node_index: int = 0, is_head: bool = False,
                 object_store_memory: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self.session_name = session_name
        self.node_id = NodeID.from_random().hex()
        self.gcs_address = tuple(gcs_address)
        self.is_head = is_head
        self.node_index = node_index
        self.labels = dict(labels or {})
        self.resources = NodeResources(ResourceSet(resources), self.labels)
        self.server = RpcServer(f"raylet-{node_index}")
        self.clients = ClientPool()
        self.address: Optional[Address] = None
        self.plasma = PlasmaDir(session_name, node_index)
        self.capacity = object_store_memory or CONFIG.object_store_memory_bytes
        self.spill_dir = spill_dir or os.path.join(
            "/tmp", f"rtpu-spill-{session_name}-{node_index}")

        self.workers: Dict[bytes, WorkerHandle] = {}
        self.queued: List[LeaseRequest] = []
        self.leases: Dict[int, Tuple[bytes, ResourceSet,
                                     Optional[Tuple[PlacementGroupID, int]]]] = {}
        self.bundles: Dict[Tuple[PlacementGroupID, int], BundleAccount] = {}
        self.objects: Dict[str, ObjectEntry] = {}
        self.store_used = 0
        # Spill/restore accounting (reference: local_object_manager.cc
        # spilled_bytes_total/restored_bytes_total + the pinned-bytes
        # gauge): feeds runtime_metrics and get_memory_report.
        self.spilled_objects: Dict[str, int] = {}  # hex -> size
        self.spilled_bytes = 0
        self.spilled_bytes_total = 0
        self.restored_bytes_total = 0
        self.spill_count = 0
        self.restore_count = 0
        # Memory watchdog state (reference: memory_monitor.h): above the
        # watermark the node is "under pressure" — events are emitted and
        # the lease policy hook may refuse new grants.
        self._mem_pressure = False
        self._last_pressure_event = 0.0
        self.cluster_view: Dict[str, NodeView] = {}
        self._view_ver = -1  # last merged GCS view version (-1 = none)
        self._view_epoch = 0  # GCS incarnation the version belongs to
        # in-progress push-broadcast assemblies: object_hex -> state
        self._push_assembly: Dict[str, Dict[str, Any]] = {}
        from .external_storage import storage_from_config
        self.spill_storage = storage_from_config()
        self.node_addresses: Dict[str, Address] = {}
        self._next_lease_id = 0
        # Actor-lease idempotency (one grant per actor id): a caller
        # whose lease RPC timed out retries while the ORIGINAL request is
        # still queued behind the spawn pipeline — without coalescing,
        # both requests eventually grant and two creation pushes land on
        # two (or worse, one reused) worker(s), cross-wiring actors.
        self._actor_lease_tasks: Dict[str, asyncio.Task] = {}
        self._lease_actor_keys: Dict[int, str] = {}
        self._spawn_sem: Optional[asyncio.Semaphore] = None
        self._tasks: List[asyncio.Task] = []
        self._pulls: Dict[str, asyncio.Future] = {}
        # Log & forensics plane: per-worker line rings (live + a bounded
        # FIFO of dead workers' rings) and the bounded publish window
        # the pump flushes through (see logplane.py).
        self.log_rings = logplane.RingSet()
        self._log_pub_window = logplane.PublishWindow(
            CONFIG.log_pump_inflight_max)
        # GCS failover state: the incarnation we registered with (a
        # changed incarnation in any heartbeat ack means the GCS
        # restarted — re-announce), and reports whose delivery failed
        # while the GCS was down (replayed after re-registration so
        # worker deaths/events that raced the outage aren't lost).
        self._gcs_incarnation: Optional[int] = None
        self._gcs_reconnecting = False
        self._gcs_reports_pending: collections.deque = \
            collections.deque(maxlen=256)
        # Graceful-drain fence (rolling upgrades / elastic scale-in):
        # while draining, NO new lease grants — requests spill back to
        # healthy nodes or are rejected with {"draining": True}, workers
        # whose leases return are disposed instead of re-pooled, and
        # drain_self(phase="wait") blocks until in-flight leases empty
        # (stragglers past the deadline get postmortem-tagged kills).
        self._draining = False
        self._drain_reason = ""
        # Set by drain_self(exit_process=True): standalone raylet mains
        # (raylet_main.py) wait on it and exit clean after the drain.
        self.exit_requested: Optional[asyncio.Event] = None
        # Gauge hygiene: shapes whose queue-age series we exported last
        # tick, so a drained shape's stale age is zeroed, not frozen.
        self._last_age_shapes: Set[str] = set()
        self._stopped = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Address:
        self.exit_requested = asyncio.Event()
        self.server.register_instance(self)
        self.address = await self.server.start(host, port)
        gcs = self.clients.get(self.gcs_address)
        reply = await gcs.call("register_node", node_id=self.node_id,
                               address=self.address,
                               resources=self.resources.total.to_dict(),
                               labels=self.labels, is_head=self.is_head,
                               retries=CONFIG.rpc_max_retries)
        if isinstance(reply, dict):
            self._gcs_incarnation = reply.get("incarnation")
        self._tasks.append(asyncio.ensure_future(self._heartbeat_loop()))
        self._tasks.append(asyncio.ensure_future(self._worker_liveness_loop()))
        if CONFIG.memory_monitor_refresh_ms > 0:
            self._tasks.append(
                asyncio.ensure_future(self._memory_monitor_loop()))
        from . import profiler
        profiler.maybe_autostart()
        return self.address

    async def stop(self):
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        for handle in list(self.workers.values()):
            self._kill_worker(handle)
        await self.server.stop()
        self.plasma.destroy()

    # ------------------------------------------------------------------
    # heartbeats / cluster view
    # ------------------------------------------------------------------

    async def _heartbeat_loop(self):
        gcs = self.clients.get(self.gcs_address)
        next_metrics_flush = 0.0
        hb_failures = 0
        while not self._stopped:
            try:
                self._update_metrics()
                now = time.monotonic()
                if now >= next_metrics_flush:
                    next_metrics_flush = now + \
                        CONFIG.metrics_report_interval_s
                    self._flush_metrics(gcs)
                reply = await gcs.call(
                    "heartbeat", node_id=self.node_id,
                    resources_available=self.resources.available.to_dict(),
                    resources_total=self.resources.total.to_dict(),
                    pending_demand=[req.demand.to_dict()
                                    for req in self.queued[:100]],
                    queue_ages=self._queue_ages(),
                    draining=self._draining,
                    known_ver=self._view_ver,
                    known_epoch=self._view_epoch,
                    gcs_incarnation=self._gcs_incarnation,
                    timeout=CONFIG.health_check_timeout_s)
                if reply.get("stale_gcs"):
                    # A zombie pre-restart GCS answered (we already
                    # follow its successor): not an ack. If EVERY
                    # heartbeat says stale (the successor's state was
                    # lost and it restarted with a lower incarnation),
                    # reconnect — _reannounce stamps the server's own
                    # incarnation, so the re-registration is accepted
                    # and the cluster reforms instead of orbiting a
                    # GCS that refuses us forever.
                    logger.warning("heartbeat answered by a stale GCS "
                                   "incarnation; ignoring")
                    hb_failures += 1
                    if hb_failures >= \
                            CONFIG.gcs_heartbeat_failure_threshold:
                        await self._reconnect_to_gcs(
                            "heartbeats answered by a stale GCS "
                            "incarnation")
                        hb_failures = 0
                elif reply.get("dead"):
                    logger.warning("raylet %s marked dead by gcs; exiting",
                                   self.node_id[:12])
                    return
                elif reply.get("unknown"):
                    # The GCS restarted without our record (persistence
                    # off / lost): re-register instead of exiting.
                    await self._reconnect_to_gcs(
                        "gcs lost our registration")
                    hb_failures = 0
                else:
                    hb_failures = 0
                    inc = reply.get("incarnation")
                    if inc is not None and self._gcs_incarnation is not None \
                            and inc != self._gcs_incarnation:
                        # Restart detected between heartbeats (durable
                        # GCS knows us, so the ack still succeeded):
                        # re-announce workers + replay unacked reports.
                        await self._reconnect_to_gcs(
                            f"gcs incarnation changed "
                            f"{self._gcs_incarnation} -> {inc}")
                    elif inc is not None:
                        self._gcs_incarnation = inc
                    self._update_view(reply.get("view", {}))
                    fj = reply.get("finished_jobs")
                    if fj:
                        self._reap_job_leases(fj)
            except asyncio.CancelledError:
                return
            except Exception:
                hb_failures += 1
                if hb_failures >= CONFIG.gcs_heartbeat_failure_threshold:
                    await self._reconnect_to_gcs(
                        f"{hb_failures} consecutive heartbeat failures")
                    hb_failures = 0
                else:
                    logger.debug("heartbeat to GCS failed; retrying next "
                                 "interval", exc_info=True)
            await asyncio.sleep(HEARTBEAT_INTERVAL_S)

    # -- GCS failover: reconnect-and-replay ----------------------------

    async def _reconnect_to_gcs(self, reason: str):
        """Ride through a GCS restart: jittered-exponential probing
        until a live incarnation answers, then re-register (same
        node_id, address and resources re-announced, live worker
        inventory attached so the GCS can fail over actors whose
        workers died during the outage) and replay reports whose
        delivery was lost. Never gives up — a raylet without a GCS has
        no cluster."""
        if self._gcs_reconnecting:
            return
        self._gcs_reconnecting = True
        t0 = time.monotonic()
        try:
            gcs = self.clients.get(self.gcs_address)
            logger.warning("raylet %s reconnecting to GCS (%s)",
                           self.node_id[:12], reason)
            bo = Backoff(
                base_s=CONFIG.gcs_reconnect_base_delay_ms / 1000.0,
                max_s=CONFIG.gcs_reconnect_max_delay_ms / 1000.0)
            info = None
            while not self._stopped:
                try:
                    info = await gcs.call(
                        "gcs_info", timeout=CONFIG.health_check_timeout_s)
                    break
                except Exception:
                    logger.debug("gcs reconnect probe failed",
                                 exc_info=True)
                    await bo.async_sleep()
            if info is None:  # stopped mid-reconnect
                return
            try:
                accepted = await self._reannounce(info.get("incarnation"))
            except asyncio.CancelledError:
                raise
            except Exception:
                # The GCS died again between the probe and the register
                # (or rejected us): the next failed heartbeat re-enters
                # this loop. Must not raise — one call site is the
                # heartbeat loop's own except handler, and an escape
                # there would kill heartbeating for good.
                logger.warning("gcs re-registration failed; will retry",
                               exc_info=True)
                return
            if not accepted:
                # Fenced or stale-rejected: not a reconnect — the
                # failover dashboards must not count a refused node.
                return
            elapsed = time.monotonic() - t0
            from .runtime_metrics import runtime_metrics
            metrics = runtime_metrics()
            metrics.gcs_reconnects.inc(tags={"component": "raylet"})
            metrics.gcs_reconnect_latency.observe(
                elapsed, tags={"component": "raylet"})
            logger.warning(
                "raylet %s re-registered with GCS incarnation %s after "
                "%.2fs", self.node_id[:12], self._gcs_incarnation,
                elapsed)
        finally:
            self._gcs_reconnecting = False

    async def _reannounce(self, incarnation: Optional[int]) -> bool:
        """Re-register on the (possibly new) GCS incarnation and replay
        in-flight state: resource totals, live worker inventory, and any
        queued reports (worker deaths, events) the outage swallowed.
        Returns False when the GCS refused us (stale/fenced)."""
        gcs = self.clients.get(self.gcs_address)
        worker_ids = [h.worker_id.hex() for h in self.workers.values()
                      if h.state != "DEAD"]
        reply = await gcs.call(
            "register_node", node_id=self.node_id, address=self.address,
            resources=self.resources.total.to_dict(), labels=self.labels,
            is_head=self.is_head, worker_ids=worker_ids,
            gcs_incarnation=incarnation,
            retries=CONFIG.rpc_max_retries)
        if isinstance(reply, dict):
            if reply.get("stale_gcs"):
                logger.warning("re-registration rejected by a stale GCS")
                return False
            if reply.get("dead"):
                # Fenced out: we were declared dead and our actors
                # failed over. The next heartbeat's {"dead": True} makes
                # the heartbeat loop exit this raylet cleanly.
                logger.warning("re-registration refused: this node was "
                               "declared dead; exiting on next heartbeat")
                return False
            self._gcs_incarnation = reply.get("incarnation")
        # The new incarnation numbers its view from scratch.
        self._view_ver = -1
        self._view_epoch = 0
        # Replay unacked reports in arrival order; re-queue on failure
        # (the next reconnect cycle retries).
        pending = list(self._gcs_reports_pending)
        self._gcs_reports_pending.clear()
        for method, kwargs in pending:
            try:
                await gcs.call(method, timeout=10, **kwargs)
            except Exception:
                logger.debug("replay of %s after reconnect failed",
                             method, exc_info=True)
                self._gcs_reports_pending.append((method, kwargs))
        return True

    def _queue_gcs_report(self, method: str, kwargs: Dict[str, Any]):
        """Remember a report whose delivery failed (GCS down) for replay
        after re-registration. Bounded: oldest dropped beyond 256."""
        self._gcs_reports_pending.append((method, kwargs))

    @staticmethod
    def _shape_tag(demand: ResourceSet) -> str:
        """Compact stable tag for one lease shape's resource demand
        (the per-shape queue-age gauge + autoscaler state rows)."""
        d = demand.to_dict()
        if not d:
            return "none"
        return ",".join(f"{k}={v:g}" for k, v in sorted(d.items()))

    def _queue_ages(self) -> Dict[str, float]:
        """Oldest pending lease age per resource shape — the elastic
        autoscaler's primary scale-up signal (a deep-but-fresh queue is
        a burst; an OLD queue is starvation)."""
        now = time.monotonic()
        ages: Dict[str, float] = {}
        for req in self.queued:
            shape = self._shape_tag(req.demand)
            age = now - (req.enqueued_at or now)
            if age > ages.get(shape, -1.0):
                ages[shape] = age
        return ages

    def _update_metrics(self):
        from .runtime_metrics import runtime_metrics
        metrics = runtime_metrics()
        tags = {"node": str(self.node_index)}
        metrics.raylet_lease_queue.set(len(self.queued), tags=tags)
        metrics.node_draining.set(1 if self._draining else 0, tags=tags)
        ages = self._queue_ages()
        for shape, age in ages.items():
            metrics.lease_queue_age.set(
                age, tags={"node": str(self.node_index), "shape": shape})
        for stale in self._last_age_shapes - set(ages):
            metrics.lease_queue_age.set(
                0.0, tags={"node": str(self.node_index), "shape": stale})
        self._last_age_shapes = set(ages)
        metrics.raylet_store_bytes.set(self.store_used, tags=tags)
        metrics.raylet_workers.set(len(self.workers), tags=tags)
        metrics.store_capacity.set(self.capacity, tags=tags)
        metrics.store_pinned_bytes.set(
            sum(e.size for e in self.objects.values() if e.pinned > 0),
            tags=tags)
        metrics.store_spilled_bytes.set(self.spilled_bytes, tags=tags)
        if not CONFIG.no_log_plane:
            metrics.log_ring_bytes.set(self.log_rings.total_bytes(),
                                       tags=tags)

    def _gcs_event(self, event_type: str, message: str,
                   severity: str = "INFO", **fields):
        """Best-effort structured event to the GCS event log; failures
        (GCS down) queue for replay after reconnection."""
        gcs = self.clients.get(self.gcs_address)
        kwargs = dict(event_type=event_type, message=message,
                      severity=severity,
                      fields=dict(fields, node_id=self.node_id))
        fut = asyncio.ensure_future(gcs.call(
            "add_event", timeout=10, **kwargs))

        def _done(f):
            if not f.cancelled() and f.exception() is not None:
                self._queue_gcs_report("add_event", kwargs)
        fut.add_done_callback(_done)

    def _flush_metrics(self, gcs):
        """Push this process's registry into the metrics KV. Standalone
        raylet processes have no CoreWorker (whose flusher would do it);
        in local mode the driver's flusher owns the shared registry, so
        flushing here too would double-count counters after the merge."""
        from .core_worker import try_get_core_worker
        if try_get_core_worker() is not None:
            return
        from ..util.metrics import METRICS_KV_NS, snapshot_all_json
        fut = asyncio.ensure_future(gcs.call(
            "kv_put", ns=METRICS_KV_NS, key=f"raylet-{self.node_id}",
            value=snapshot_all_json(), overwrite=True, timeout=10))
        # best-effort: consume a failed flush (GCS briefly unreachable)
        # instead of spamming "Task exception was never retrieved"
        fut.add_done_callback(
            lambda f: f.cancelled() or f.exception())

    def _update_view(self, vd: Dict[str, Any]):
        """Merge a versioned view delta (stable cluster => empty payload;
        reference: ray_syncer.h eventually-consistent resource views)."""
        delta = vd.get("delta", vd if vd and "ver" not in vd else {})
        changed = bool(delta) or bool(vd.get("removed"))
        if vd.get("full", "ver" not in vd):
            view = {}
        else:
            view = self.cluster_view
            for nid in vd.get("removed", ()):
                view.pop(nid, None)
                self.node_addresses.pop(nid, None)
        for nid, info in delta.items():
            nr = NodeResources(ResourceSet(info["total"]), info["labels"])
            nr.available = ResourceSet(info["available"])
            nv = NodeView(nid, nr)
            # Drain fence propagation: peer raylets must stop spilling
            # lease requests onto a draining node.
            nv.draining = bool(info.get("draining"))
            view[nid] = nv
            self.node_addresses[nid] = tuple(info["address"])
        self.cluster_view = view
        if "ver" in vd:
            self._view_ver = vd["ver"]
            self._view_epoch = vd.get("epoch", 0)
        if not changed:
            return
        # New nodes / freed remote capacity can unblock queued requests via
        # spillback — a request infeasible here would otherwise park forever
        # (reference: cluster_lease_manager re-runs scheduling on every
        # resource-view change, node_manager.cc ScheduleAndGrantLeases).
        self._pump_queue()

    # ------------------------------------------------------------------
    # worker pool (reference: src/ray/raylet/worker_pool.cc)
    # ------------------------------------------------------------------

    def _env_key(self, runtime_env: Dict[str, Any]) -> Tuple:
        """Workers are dedicated per runtime environment: env vars are
        process state, and working_dir/py_modules mutate sys.path/cwd —
        none of these may leak between environments via worker reuse."""
        from .task_spec import runtime_env_key
        return runtime_env_key(runtime_env)

    def _spawn_worker(self, env_key: Tuple) -> WorkerHandle:
        worker_id = WorkerID.from_random().binary()
        env = dict(os.environ)
        env.update({k: v for k, v in env_key[0]})  # env_vars component
        # Workers must import ray_tpu even when it isn't installed — put the
        # package's parent dir on their PYTHONPATH.
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (pkg_root + os.pathsep + existing
                                 if existing else pkg_root)
        env.update({
            # piped stdout must not sit in an 8KB block buffer — the log
            # stream to the driver needs lines as they are printed
            "PYTHONUNBUFFERED": "1",
            "RTPU_WORKER_ID": worker_id.hex(),
            "RTPU_SESSION": self.session_name,
            "RTPU_NODE_ID": self.node_id,
            "RTPU_NODE_INDEX": str(self.node_index),
            "RTPU_RAYLET_ADDR": f"{self.address[0]}:{self.address[1]}",
            "RTPU_GCS_ADDR": f"{self.gcs_address[0]}:{self.gcs_address[1]}",
        })
        # Workers must not inherit the driver's TPU chip lock unless the
        # lease assigns chips (runtime-env env_vars / accelerator hook).
        # FORCE cpu — setdefault is not enough: on TPU hosts the ambient
        # environment itself carries JAX_PLATFORMS=tpu/axon, and a worker
        # inheriting it would grab the host chip AND run TPU kernels on
        # shapes meant for the CPU fallback.
        if not any(k == "JAX_PLATFORMS" for k, _ in env_key[0]):
            env["JAX_PLATFORMS"] = env.get("RTPU_WORKER_JAX_PLATFORMS",
                                           "cpu")
        platforms = env["JAX_PLATFORMS"] or \
            env.get("RTPU_WORKER_JAX_PLATFORMS", "")
        if platforms and "tpu" not in platforms and "axon" not in platforms:
            # CPU-only workers skip the TPU site hook (it imports jax at
            # interpreter startup — seconds of cold-start per worker).
            # Empty platforms means auto-detect (TPU train workers are
            # launched with JAX_PLATFORMS="" exactly so they grab the
            # chip) — those must keep the hook.
            env["PALLAS_AXON_POOL_IPS"] = ""
        handle = WorkerHandle(
            worker_id=worker_id, proc=None, pid=0, env_key=env_key,
            registered=asyncio.get_running_loop().create_future())
        self.workers[worker_id] = handle
        loop = asyncio.get_running_loop()

        def _popen():
            # fork/exec off the event loop: a spawn burst must not starve
            # lease/heartbeat handling (1-core boxes stall for seconds).
            # With log_to_driver, worker output is piped and streamed to
            # the driver via GCS pubsub (reference: _private/log_monitor.py).
            from .task_spec import (ENV_KEY_CONDA, ENV_KEY_PYTHON_ENV,
                                    ENV_KEY_UV)
            interpreter = sys.executable
            pyenv_reqs = env_key[ENV_KEY_PYTHON_ENV] \
                if len(env_key) > ENV_KEY_PYTHON_ENV else ()
            conda_entry = env_key[ENV_KEY_CONDA] \
                if len(env_key) > ENV_KEY_CONDA else ""
            uv_pkgs = env_key[ENV_KEY_UV] \
                if len(env_key) > ENV_KEY_UV else ""
            if pyenv_reqs or conda_entry or uv_pkgs:
                # isolated interpreter (reference: conda/uv/pip plugins)
                from .errors import RuntimeEnvSetupError
                from .runtime_env import (ensure_conda_env_entry,
                                          ensure_python_env,
                                          ensure_uv_env)
                pyenv_root = os.path.join(
                    "/tmp", "rtpu", f"session_{self.session_name}",
                    "pyenvs")
                try:
                    if conda_entry:
                        interpreter = ensure_conda_env_entry(
                            conda_entry, pyenv_root)
                    elif uv_pkgs:
                        interpreter = ensure_uv_env(
                            list(uv_pkgs), pyenv_root)
                    else:
                        interpreter = ensure_python_env(
                            list(pyenv_reqs), pyenv_root)
                except Exception as e:
                    # Deterministic: the same requirements will fail the
                    # same way on every node — callers must not retry.
                    raise RuntimeEnvSetupError(
                        f"python env setup failed: {e}") from e
            if CONFIG.no_log_plane:
                # exact-legacy wiring (the kill switch's contract)
                if CONFIG.log_to_driver:
                    out_target = err_target = subprocess.PIPE
                else:
                    # stderr stays inherited: crash tracebacks must
                    # surface somewhere even with log streaming disabled
                    out_target, err_target = subprocess.DEVNULL, None
            else:
                # Log plane: ALWAYS pipe — the per-worker ring captures
                # (and postmortems quote) output even when pubsub
                # streaming to drivers is off. The old DEVNULL path
                # becomes ring-only capture.
                out_target = err_target = subprocess.PIPE
            argv = [interpreter, "-m", "ray_tpu._internal.worker_main"]
            from .task_spec import ENV_KEY_IMAGE_URI
            image_uri = env_key[ENV_KEY_IMAGE_URI] \
                if len(env_key) > ENV_KEY_IMAGE_URI else ""
            if image_uri:
                from .runtime_env import build_container_argv
                # the IMAGE's python, not the host interpreter path
                # (host venv paths don't exist inside the container);
                # ray_tpu resolves via the mounted pkg_root + the
                # forwarded PYTHONPATH
                argv = ["python", "-m", "ray_tpu._internal.worker_main"]
                argv = build_container_argv(
                    image_uri, argv, env, pkg_root,
                    extra_env_keys=[k for k, _ in env_key[0]])
            return subprocess.Popen(
                argv, env=env, stdout=out_target, stderr=err_target)

        def _attach(fut):
            try:
                proc = fut.result()
            except Exception as e:
                logger.warning("worker spawn failed: %s", e)
                self.workers.pop(worker_id, None)
                if not handle.registered.done():
                    # Preserve the exception type: RuntimeEnvSetupError is
                    # deterministic (permanent rejection); a Popen/OS error
                    # (ENOMEM/EAGAIN under spawn bursts) is transient and
                    # must stay retryable.
                    from .errors import RuntimeEnvSetupError
                    if isinstance(e, RuntimeEnvSetupError):
                        handle.registered.set_exception(e)
                    else:
                        handle.registered.set_exception(
                            RuntimeError(f"worker spawn failed: {e}"))
                return
            handle.proc = proc
            handle.pid = proc.pid
            if proc.stdout is not None or proc.stderr is not None:
                self._start_log_forwarders(proc, handle)
            if handle.state == "DEAD":
                # killed while the fork was in flight — don't leak it
                try:
                    proc.terminate()
                except Exception:
                    logger.debug("terminate of orphaned spawn failed",
                                 exc_info=True)
        spawn_fut = loop.run_in_executor(None, _popen)
        spawn_fut.add_done_callback(_attach)
        return handle

    def _start_log_forwarders(self, proc: subprocess.Popen,
                              handle: "WorkerHandle" = None):
        """Tail the worker's stdout/stderr pipes: capture lines into the
        per-worker ring (attribution stamps parsed off), and publish
        cleaned batches to the WORKER_LOGS pubsub channel when
        log_to_driver streaming is on (reference:
        _private/log_monitor.py -> driver prints them). Under
        RTPU_NO_LOG_PLANE the pump degrades to the exact-legacy
        publish-only behavior (and only runs when log_to_driver piped
        the streams at all)."""
        from .rpc import EventLoopThread

        gcs = self.clients.get(self.gcs_address)
        capture = not CONFIG.no_log_plane
        forward = CONFIG.log_to_driver
        window = self._log_pub_window
        ring = self.log_rings.get_or_create(
            handle.worker_id.hex(), proc.pid) if capture \
            and handle is not None else None
        limiter = logplane.RateLimiter(
            CONFIG.log_rate_limit_lines_per_s) if capture else None
        from .runtime_metrics import runtime_metrics
        metrics = runtime_metrics()
        node_tag = str(self.node_index)

        def _pump(stream, name):
            batch: List[str] = []
            last_flush = time.monotonic()

            def _ingest(raw: str):
                """One raw pumped line -> ring capture + (maybe) the
                forward batch. Returns with the batch updated; the ring
                always captures, streaming is what rate limits."""
                if not capture or ring is None:
                    batch.append(raw)
                    return
                attribution, msg = logplane.parse_line(raw)
                if ring.job is None and handle is not None:
                    # the lease that binds this worker to a job lands
                    # after spawn; adopt it as soon as it exists
                    ring.job = handle.job_hex
                entry = ring.append(
                    name, attribution["level"], msg,
                    task=attribution["task"], actor=attribution["actor"],
                    job=attribution["job"])
                metrics.log_lines.inc(tags={
                    "node": node_tag, "stream": name,
                    "level": entry["level"]})
                overflow = ring.take_overflow_delta()
                if overflow:
                    metrics.log_dropped.inc(overflow, tags={
                        "node": node_tag, "reason": "ring_overflow"})
                if forward:
                    if limiter is None or limiter.allow(1):
                        batch.append(msg)
                    else:
                        metrics.log_dropped.inc(tags={
                            "node": node_tag, "reason": "rate_limited"})

            def flush():
                nonlocal batch, last_flush
                if not batch:
                    return
                lines, batch = batch, []
                last_flush = time.monotonic()
                if capture and not forward:
                    return  # ring-only mode: nothing streams
                # job read at flush time: the lease that binds this worker
                # to a job lands after spawn; drivers filter on it so one
                # job's output doesn't print on every driver
                job = handle.job_hex if handle is not None else None
                # Bounded in-flight window: with the GCS down/slow,
                # batches DROP (counted, warned once) instead of
                # queueing unboundedly on the EventLoopThread. Applies
                # in kill-switch mode too (the unbounded queue was a
                # bug, not plane behavior) — but only the plane moves
                # rtpu_log_* metrics; off-mode drops are visible via
                # the PublishWindow's own counters + warning.
                if not window.try_acquire(len(lines)):
                    if capture:
                        metrics.log_dropped.inc(
                            len(lines),
                            tags={"node": node_tag,
                                  "reason": "backpressure"})
                    return

                async def _publish(lines=lines, job=job):
                    try:
                        await gcs.call(
                            "publish", channel="WORKER_LOGS",
                            message={"pid": proc.pid,
                                     "node_id": self.node_id,
                                     "stream": name, "job": job,
                                     "lines": lines},
                            timeout=10)
                    except Exception:
                        logger.debug("WORKER_LOGS publish failed",
                                     exc_info=True)
                    finally:
                        window.release()
                EventLoopThread.get().post(_publish())
            # Raw nonblocking fd reads with our own line splitting.
            # select + BufferedReader.readline() is WRONG here: readline
            # slurps a whole chunk into the Python buffer and returns one
            # line — the rest sit buffered while select watches an empty
            # fd, so a burst (a stack dump, a traceback) surfaces one
            # line per future write.
            # selectors (epoll), NOT select(): select() rejects fds
            # >= FD_SETSIZE (1024), which a 1,000-actor fleet exceeds —
            # the pump then dies and that worker's logs vanish.
            import fcntl
            import selectors
            fd = stream.fileno()
            flags = fcntl.fcntl(fd, fcntl.F_GETFL)
            fcntl.fcntl(fd, fcntl.F_SETFL, flags | os.O_NONBLOCK)
            sel = selectors.DefaultSelector()
            sel.register(fd, selectors.EVENT_READ)
            pending = b""
            try:
                while True:
                    ready = sel.select(timeout=0.1)
                    if not ready:
                        flush()
                        continue
                    try:
                        chunk = os.read(fd, 65536)
                    except BlockingIOError:
                        continue
                    if not chunk:
                        break
                    pending += chunk
                    *lines, pending = pending.split(b"\n")
                    for raw in lines:
                        _ingest(raw.decode("utf-8", "replace"))
                    if len(batch) >= 100 or \
                            time.monotonic() - last_flush > 0.1:
                        flush()
            except (ValueError, OSError) as e:
                # fd closed at worker teardown is a clean exit; a read
                # failure while the worker LIVES still deserves a line
                if proc.poll() is None:
                    logger.warning(
                        "worker log pump read failed (pid %s): %s",
                        proc.pid, e)
            except Exception:
                logger.exception("worker log pump failed (pid %s)",
                                 proc.pid)
            finally:
                sel.close()
                if pending:
                    _ingest(pending.decode("utf-8", "replace"))
                flush()
        from .threads import spawn_daemon
        for stream, name in ((proc.stdout, "stdout"),
                             (proc.stderr, "stderr")):
            if stream is not None:
                # Exits on its own when the worker's fd closes; tracked
                # but not joined (the fd outlives raylet teardown).
                spawn_daemon(_pump, args=(stream, name),
                             name=f"rtpu-log-{proc.pid}")

    async def handle_register_worker(self, worker_id: bytes, address: Address,
                                     pid: int):
        handle = self.workers.get(worker_id)
        if handle is None:
            # Worker from a previous epoch; tell it to exit.
            return {"exit": True}
        handle.address = tuple(address)
        handle.pid = pid
        if handle.registered and not handle.registered.done():
            # A spawning lease request is awaiting THIS worker: hold it
            # in STARTING so the idle-pool scans cannot steal it between
            # registration and the spawner's resume — the stolen-worker
            # interleaving leased one process to two actor creations.
            handle.registered.set_result(True)
        else:
            handle.state = "IDLE"
            handle.last_idle = time.monotonic()
        return {"exit": False, "node_id": self.node_id,
                "node_index": self.node_index}

    async def _worker_liveness_loop(self):
        while not self._stopped:
            try:
                await asyncio.sleep(CONFIG.worker_liveness_check_period_s)
                now = time.monotonic()
                dead: List[WorkerHandle] = []
                for handle in list(self.workers.values()):
                    if handle.proc is not None and handle.proc.poll() is not None \
                            and handle.state != "DEAD":
                        dead.append(handle)
                    elif (handle.state == "IDLE" and not handle.is_actor_worker
                          and now - handle.last_idle >
                          CONFIG.worker_idle_timeout_s):
                        self._kill_worker(handle)
                if dead:
                    # concurrent: a mass death (OOM storm, job teardown)
                    # must not serialize at one postmortem grace sleep +
                    # GCS report per worker — callers poll the GCS for
                    # these postmortems on a ~2s budget
                    results = await asyncio.gather(
                        *(self._on_worker_death(h) for h in dead),
                        return_exceptions=True)
                    for handle, res in zip(dead, results):
                        if isinstance(res, Exception):
                            logger.error(
                                "death handling for worker %s failed: "
                                "%r", handle.worker_id.hex()[:12], res)
                # Reap abandoned push assemblies (sender died mid-stream).
                for ohex, assy in list(self._push_assembly.items()):
                    if now - assy["t"] > 120:
                        self._push_assembly.pop(ohex, None)
                        try:
                            assy["buf"].release()
                            self.plasma.abort(ObjectID.from_hex(ohex))
                        except Exception:
                            logger.debug("abort of half-pushed object %s "
                                         "failed", ohex[:12], exc_info=True)
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("worker liveness loop error")

    def _reap_job_leases(self, finished_jobs: List[str]):
        """Kill workers leased to finished/dead jobs and refund their
        resources; drop the jobs' queued lease requests (reference:
        node_manager.cc HandleJobFinished). Idempotent — the GCS resends
        recently finished jobs on every heartbeat."""
        jobs = set(finished_jobs)
        for handle in list(self.workers.values()):
            if handle.job_hex in jobs and handle.lease_id is not None \
                    and handle.state != "DEAD":
                logger.info("reaping worker %s leased to finished job %s",
                            handle.worker_id.hex()[:12], handle.job_hex[:8])
                lease_id = handle.lease_id
                self._kill_worker(handle)
                self._release_lease(lease_id)
        for req in list(self.queued):
            if req.spec_meta.get("job") in jobs:
                self.queued.remove(req)
                if not req.future.done():
                    req.future.set_result({"canceled": True})

    async def _on_worker_death(self, handle: WorkerHandle):
        # Single-flight: the liveness sweep and a caller's dispose
        # (handle_return_worker) can both spot the same death. Whoever
        # sets DEAD first (synchronously below — no await before it, so
        # same-loop callers can't interleave) owns the postmortem; the
        # loser must neither re-report nor touch the ring while the
        # owner's grace sleep is still draining it.
        if handle.state == "DEAD":
            return
        # Actor workers routinely die on purpose (ray.kill / job teardown
        # kill_actor goes GCS->worker directly); the GCS owns their
        # restart-or-fail decision, so that's not warning-worthy here.
        log = logger.info if handle.is_actor_worker else logger.warning
        log("worker %s (pid %s) died unexpectedly",
            handle.worker_id.hex()[:12], handle.pid)
        handle.state = "DEAD"
        self.workers.pop(handle.worker_id, None)
        if handle.lease_id is not None:
            self._release_lease(handle.lease_id)
        # Assemble the postmortem BEFORE retiring the ring: exit
        # taxonomy + the ring's last lines + recent task ids + the
        # stuck-task stack dump if the probe sweeper captured one. It
        # rides the death report so the GCS can attach it to the
        # WORKER_DIED event and serve it to crashing callers.
        postmortem = None
        if not CONFIG.no_log_plane:
            # One pump tick of grace so lines still buffered in the dead
            # worker's pipe reach the ring before we quote it (the pump
            # polls every 0.1s; its EOF drain flushes the remainder).
            await asyncio.sleep(0.2)
            whex = handle.worker_id.hex()
            postmortem = logplane.build_postmortem(
                worker_hex=whex, pid=handle.pid, node_id=self.node_id,
                returncode=handle.proc.returncode
                if handle.proc is not None else None,
                ring=self.log_rings.live.get(whex),
                kill_reason=handle.kill_reason,
                cause="worker process died")
            self.log_rings.retire(whex)
        report = dict(node_id=self.node_id, worker_id=handle.worker_id,
                      cause="worker process died", postmortem=postmortem)
        try:
            await self.clients.get(self.gcs_address).call(
                "report_worker_death", timeout=10, **report)
        except Exception:
            # GCS down: queue for replay after re-registration — a death
            # that races the outage must still fail its actor over.
            logger.debug("report_worker_death to GCS failed; queued for "
                         "reconnect replay", exc_info=True)
            self._queue_gcs_report("report_worker_death", report)

    # ------------------------------------------------------------------
    # memory monitor (reference: src/ray/common/memory_monitor.h:52 +
    # raylet/worker_killing_policy.h:39 retriable-FIFO variant)
    # ------------------------------------------------------------------

    @staticmethod
    def _system_memory_usage_fraction() -> float:
        """Used fraction of system memory from /proc/meminfo."""
        try:
            fields = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    name, _, rest = line.partition(":")
                    fields[name] = int(rest.split()[0])
            total = fields.get("MemTotal", 0)
            avail = fields.get("MemAvailable", total)
            if total <= 0:
                return 0.0
            return 1.0 - avail / total
        except OSError:  # pragma: no cover
            return 0.0

    # Overridable for tests / fake pressure injection.
    _memory_usage_fn = None

    async def _memory_monitor_loop(self):
        period = CONFIG.memory_monitor_refresh_ms / 1000.0
        from .runtime_metrics import runtime_metrics
        tags = {"node": str(self.node_index)}
        while not self._stopped:
            try:
                await asyncio.sleep(period)
                usage_fn = (self._memory_usage_fn
                            or self._system_memory_usage_fraction)
                usage = usage_fn()
                runtime_metrics().node_mem_used_ratio.set(usage, tags=tags)
                over_watermark = usage > CONFIG.memory_monitor_watermark
                if over_watermark and not self._mem_pressure:
                    logger.warning(
                        "node memory %.1f%% above watermark %.1f%%",
                        usage * 100, CONFIG.memory_monitor_watermark * 100)
                pressure_cleared = self._mem_pressure and not over_watermark
                self._mem_pressure = over_watermark
                if pressure_cleared:
                    # Requests parked while leases were refused must not
                    # wait for an unrelated release/view change to grant.
                    self._pump_queue()
                now = time.monotonic()
                if over_watermark and \
                        now - self._last_pressure_event > 30.0:
                    # Rate-limited: a node camped above the watermark
                    # must not flood the event log every refresh tick.
                    self._last_pressure_event = now
                    self._gcs_event(
                        "MEMORY_PRESSURE",
                        f"node memory at {usage * 100:.1f}% (watermark "
                        f"{CONFIG.memory_monitor_watermark * 100:.0f}%)",
                        severity="WARNING", used_ratio=usage)
                if usage > CONFIG.memory_usage_threshold:
                    self._kill_for_memory(usage)
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("memory monitor loop error")

    def _kill_for_memory(self, usage: float):
        """Retriable-FIFO policy: kill the most recently leased
        task-worker first (its owner retries it), sparing actor workers
        as long as possible; at most one kill per refresh tick."""
        leased = [w for w in self.workers.values()
                  if w.state == "LEASED" and w.proc is not None]
        if not leased:
            return
        leased.sort(key=lambda w: ((0 if not w.is_actor_worker else 1),
                                   -(w.lease_id or 0)))
        victim = leased[0]
        consequence = ("callers see ActorDiedError unless max_restarts "
                       "allows a restart" if victim.is_actor_worker
                       else "the owner will retry retriable tasks")
        logger.warning(
            "memory usage %.1f%% above threshold %.1f%%: killing worker "
            "%s (pid %s, %s) to relieve pressure; %s",
            usage * 100, CONFIG.memory_usage_threshold * 100,
            victim.worker_id.hex()[:12], victim.pid,
            "actor" if victim.is_actor_worker else "task", consequence)
        victim.kill_reason = "memory"  # postmortem taxonomy: OOM_KILLED
        try:
            victim.proc.kill()
        except Exception:
            logger.debug("memory-kill of pid %s failed (already gone?)",
                         victim.pid, exc_info=True)

    def _kill_worker(self, handle: WorkerHandle):
        handle.state = "DEAD"
        self.workers.pop(handle.worker_id, None)
        if not CONFIG.no_log_plane:
            # intentional teardown: no postmortem, but the ring moves to
            # the dead FIFO so `cli logs` still answers for a while
            self.log_rings.retire(handle.worker_id.hex())
        if handle.proc is not None:
            try:
                handle.proc.terminate()
            except Exception:
                logger.debug("terminate of worker pid %s failed",
                             handle.pid, exc_info=True)

    # ------------------------------------------------------------------
    # leases (reference: node_manager.cc HandleRequestWorkerLease +
    # local_lease_manager.cc + cluster_lease_manager spillback)
    # ------------------------------------------------------------------

    async def handle_request_worker_lease(
            self, spec_meta: Optional[Dict[str, Any]] = None,
            meta_blob: Optional[bytes] = None,
            task_hex: Optional[str] = None, job: Optional[str] = None,
            strategy: Optional[str] = None):
        if meta_blob is not None:
            # Flat-wire lease path: the submitter pre-encodes the shape-
            # invariant meta ONCE per shape and ships the same opaque
            # blob on every request (and every spillback hop) — only the
            # tiny per-task overlay travels uncoded. Decode here, once,
            # into the dict the scheduling pipeline already understands.
            from . import serialization
            spec_meta = serialization.loads(meta_blob)
            if task_hex is not None:
                spec_meta["task_hex"] = task_hex  # lease cancellation key
            if job is not None:
                spec_meta["job"] = job            # log-stream routing
            if strategy is not None:
                spec_meta["strategy"] = strategy
        actor_key = spec_meta.get("actor_id") \
            if spec_meta.get("is_actor") else None
        if actor_key is None:
            return await self._lease_request(spec_meta)
        task = self._actor_lease_tasks.get(actor_key)
        if task is None:
            task = asyncio.ensure_future(self._lease_request(spec_meta))
            self._actor_lease_tasks[actor_key] = task
        try:
            # shield: a retry RPC joining late must not cancel the shared
            # in-flight grant when its own transport drops
            reply = await asyncio.shield(task)
        except Exception:
            # Guard the pop: a LATE-waking awaiter of a finished (failed)
            # task must not evict the NEWER in-flight task a fresh retry
            # already installed under this key — popping it would let two
            # concurrent grants coalesce onto nothing and double-lease.
            if self._actor_lease_tasks.get(actor_key) is task:
                self._actor_lease_tasks.pop(actor_key, None)
            raise
        lease_id = reply.get("lease_id")
        if lease_id is None:
            # rejection/spillback: no lease to coalesce on — clear so a
            # later attempt can try fresh (same late-waker guard as above)
            if self._actor_lease_tasks.get(actor_key) is task:
                self._actor_lease_tasks.pop(actor_key, None)
        else:
            # cache the grant until the lease dies (_release_lease), so
            # any further retry of this actor reuses the SAME worker
            self._lease_actor_keys[lease_id] = actor_key
        return reply

    async def _lease_request(self, spec_meta: Dict[str, Any]):
        self._next_lease_id += 1
        req = LeaseRequest(
            lease_id=self._next_lease_id,
            demand=ResourceSet(spec_meta.get("resources", {})),
            spec_meta=spec_meta,
            future=asyncio.get_running_loop().create_future(),
            pg=spec_meta.get("pg"),
            enqueued_at=time.monotonic())
        if self._draining:
            # Drain fence: this node grants nothing new.
            # grant_or_reject callers (the GCS actor scheduler) have a
            # two-outcome contract — grant or {"rejected"} — so they
            # get a transient rejection (their own view skips draining
            # nodes on the re-pick); everyone else is redirected to a
            # healthy node when one fits, else told WHY
            # ({"draining": True}) so the driver's retry loop goes
            # back to its local raylet instead of spinning here.
            if spec_meta.get("grant_or_reject"):
                return {"rejected": True, "draining": True,
                        "error": "node is draining"}
            spill = self._pick_spillback(req)
            if spill is not None:
                return {"spillback_to": spill}
            return {"rejected": True, "draining": True,
                    "error": "node is draining"}
        if spec_meta.get("strategy") == "SPREAD":
            # Round-robin across schedulable nodes BEFORE considering a
            # local grant (reference: spread_scheduling_policy — default
            # hybrid prefers local, SPREAD must not).
            self._spread_clock = getattr(self, "_spread_clock", 0) + 1
            target = scheduling_policy.pick_spread(
                self.cluster_view, req.demand, self._spread_clock,
                spec_meta.get("label_selector") or None)
            if target is not None and target != self.node_id:
                addr = self.node_addresses.get(target)
                if addr is not None:
                    return {"spillback_to": (target, addr)}
        grant = self._try_grant(req)
        if grant is not None:
            try:
                return await grant
            except Exception as e:  # noqa: BLE001 — never hang the caller
                logger.exception("lease grant failed")
                self._refund(req.demand, req.pg)
                return {"rejected": True, "error": f"grant failed: {e!r}"}
        if spec_meta.get("grant_or_reject"):
            reply = {"rejected": True}
            if self._refuse_new_leases():
                reply["error"] = "node under memory pressure"
            return reply
        # Spillback: is some other node better placed right now?
        spill = self._pick_spillback(req)
        if spill is not None:
            return {"spillback_to": spill}
        self.queued.append(req)
        return await req.future

    def _pick_spillback(self, req: LeaseRequest) -> Optional[Tuple[str, Address]]:
        if req.pg is not None:
            return None  # PG leases are node-pinned by the bundle
        selector = req.spec_meta.get("label_selector") or None
        target = scheduling_policy.pick_hybrid(
            self.cluster_view, req.demand, local_node_id=self.node_id,
            label_selector=selector)
        if target is not None and target != self.node_id:
            view = self.cluster_view.get(target)
            if view is not None and view.available(req.demand):
                addr = self.node_addresses.get(target)
                if addr is not None:
                    return (target, addr)
        return None

    def _refuse_new_leases(self) -> bool:
        """Watchdog policy hook: above the memory watermark (with
        memory_pressure_refuse_leases on) NEW leases stop granting —
        requests queue (or spill back) and the monitor pumps the queue
        when pressure clears; existing leases run on."""
        return self._mem_pressure and CONFIG.memory_pressure_refuse_leases

    def _try_grant(self, req: LeaseRequest):
        """Attempt to allocate resources + a worker; returns awaitable reply
        or None if resources unavailable."""
        if self._draining:
            # Drain fence: grants stop the moment the drain begins —
            # including re-grants of just-returned workers to queued
            # requests (the drain-leak the return path used to allow).
            return None
        if self._refuse_new_leases():
            return None
        if req.pg is not None:
            pg_id, index = req.pg
            if index >= 0:
                key = (pg_id, index)
                account = self.bundles.get(key)
            else:
                # wildcard bundle index: any committed bundle of this pg
                key, account = next(
                    ((k, a) for k, a in self.bundles.items()
                     if k[0] == pg_id and a.committed
                     and req.demand.fits(a.available)), (None, None))
            if account is None or not account.committed \
                    or not req.demand.fits(account.available):
                return None
            account.available = account.available - req.demand
            req.pg = key  # resolved bundle; release refunds exactly this one
            charge_node = False
        else:
            if not self.resources.try_allocate(req.demand):
                return None
            charge_node = True
        return self._finish_grant(req, charge_node)

    def _refund(self, demand: ResourceSet,
                pg_key: Optional[Tuple[PlacementGroupID, int]]):
        if pg_key is not None:
            account = self.bundles.get(pg_key)
            if account is not None:
                account.available = account.available + demand
        else:
            self.resources.release(demand)

    async def _finish_grant(self, req: LeaseRequest, charge_node: bool):
        env_key = self._env_key(req.spec_meta.get("runtime_env", {}))
        handle = next(
            (w for w in self.workers.values()
             if w.state == "IDLE" and w.env_key == env_key
             and not w.is_actor_worker), None)
        if handle is None:
            # Bounded spawn pipeline (reference: worker_pool.cc
            # maximum_startup_concurrency): a 1,000-actor burst must not
            # fork 1,000 interpreters at once on one box — spawns run
            # `maximum_startup_concurrency` at a time and the start
            # timeout covers only the spawn itself, not the queue wait.
            if self._spawn_sem is None:
                self._spawn_sem = asyncio.Semaphore(
                    max(1, CONFIG.maximum_startup_concurrency))
            async with self._spawn_sem:
                # a worker may have gone idle while we queued
                handle = next(
                    (w for w in self.workers.values()
                     if w.state == "IDLE" and w.env_key == env_key
                     and not w.is_actor_worker), None)
                if handle is None:
                    handle = self._spawn_worker(env_key)
                    try:
                        await asyncio.wait_for(
                            handle.registered,
                            CONFIG.worker_start_timeout_s)
                    except asyncio.TimeoutError:
                        self._kill_worker(handle)
                        self._refund(req.demand,
                                     None if charge_node else req.pg)
                        return {"rejected": True,
                                "error": "worker failed to start in time"}
                    except Exception as e:
                        self._kill_worker(handle)
                        self._refund(req.demand,
                                     None if charge_node else req.pg)
                        # Only deterministic runtime-env failures are
                        # permanent; transient OS errors (fork ENOMEM/
                        # EAGAIN during spawn bursts) stay retryable like
                        # the start-timeout path.
                        from .errors import RuntimeEnvSetupError
                        permanent = isinstance(e, RuntimeEnvSetupError)
                        reply = {"rejected": True, "error": str(e)}
                        if permanent:
                            reply["permanent"] = True
                        return reply
        handle.state = "LEASED"
        handle.lease_id = req.lease_id
        handle.is_actor_worker = bool(req.spec_meta.get("is_actor"))
        handle.job_hex = req.spec_meta.get("job")
        from .runtime_metrics import runtime_metrics
        runtime_metrics().raylet_leases_granted.inc(
            tags={"node": str(self.node_index)})
        self.leases[req.lease_id] = (
            handle.worker_id, req.demand, None if charge_node else req.pg)
        return {"rejected": False, "lease_id": req.lease_id,
                "worker_address": handle.address,
                "worker_id": handle.worker_id, "node_id": self.node_id}

    def _release_lease(self, lease_id: int):
        actor_key = self._lease_actor_keys.pop(lease_id, None)
        if actor_key is not None:
            self._actor_lease_tasks.pop(actor_key, None)
        entry = self.leases.pop(lease_id, None)
        if entry is None:
            return
        worker_id, demand, pg = entry
        if not demand.is_empty() or pg is not None:
            self._refund(demand, pg)
        handle = self.workers.get(worker_id)
        if handle is not None and handle.state == "LEASED":
            if handle.is_actor_worker:
                # Actor workers are SINGLE-USE (reference: dedicated
                # actor workers die with their actor): re-entering the
                # IDLE pool while the instance lives would let a later
                # creation bind a second actor onto this process and
                # cross-wire both handles. Whatever released the lease,
                # the process goes down with it — and the death is
                # REPORTED, so if a live actor was bound here the GCS
                # restarts or fails it instead of leaving its callers
                # hanging on a dead address.
                logger.info("disposing actor worker %s on lease %d "
                            "release", handle.worker_id.hex()[:12],
                            lease_id)
                self._kill_worker(handle)
                report = dict(
                    node_id=self.node_id, worker_id=handle.worker_id,
                    cause="actor worker disposed on lease release")
                fut = asyncio.ensure_future(self.clients.get(
                    self.gcs_address).call(
                        "report_worker_death", timeout=10, **report))
                fut.add_done_callback(
                    lambda f, r=report: (not f.cancelled()
                                         and f.exception() is not None
                                         and self._queue_gcs_report(
                                             "report_worker_death", r)))
            elif self._draining:
                # Drain fence on the return path: a worker returned
                # mid-drain (including via handle_return_worker's
                # grace-poll, which awaits and can resume AFTER the
                # fence went up) must NOT re-enter the idle pool where
                # a queued request from another job could re-lease it —
                # that leak kept drains from ever converging. The
                # process is disposed; its resources were refunded
                # above, so the drain's lease count still converges.
                logger.info("disposing worker %s returned during drain",
                            handle.worker_id.hex()[:12])
                self._kill_worker(handle)
            else:
                handle.state = "IDLE"
                handle.lease_id = None
                handle.last_idle = time.monotonic()
        self._pump_queue()

    def _pump_queue(self):
        still_queued = []
        for req in self.queued:
            grant = self._try_grant(req)
            if grant is not None:
                async def _complete(req=req, grant=grant):
                    try:
                        reply = await grant
                    except Exception as e:  # noqa: BLE001 — a raised
                        # grant must NOT leave the queued request's
                        # future unresolved (the driver would wait on the
                        # lease RPC forever and every task behind that
                        # waiter wedges)
                        logger.exception("queued lease grant failed")
                        self._refund(req.demand, req.pg)
                        reply = {"rejected": True,
                                 "error": f"grant failed: {e!r}"}
                    if not req.future.done():
                        req.future.set_result(reply)
                asyncio.ensure_future(_complete())
                continue
            spill = self._pick_spillback(req)
            if spill is not None and not req.future.done():
                # Debit the snapshot so one freed remote slot doesn't spill
                # the whole queue there in a herd (each bounce burns one of
                # the client's spillback hops).
                target_view = self.cluster_view.get(spill[0])
                if target_view is not None:
                    target_view.resources.available = \
                        target_view.resources.available - req.demand
                req.future.set_result({"spillback_to": spill})
                continue
            still_queued.append(req)
        self.queued = still_queued

    async def handle_agent_stats(self) -> Dict[str, Any]:
        """Per-node agent surface (reference: dashboard/agent.py +
        modules/reporter/reporter_agent.py — each node reports its own
        cpu/mem and per-worker process stats; the dashboard head proxies
        /api/nodes/<id>/stats here instead of running a separate agent
        process — the raylet IS the node agent)."""
        stats: Dict[str, Any] = {"node_id": self.node_id,
                                 "node_index": self.node_index}
        try:
            with open("/proc/loadavg") as f:
                stats["loadavg"] = [float(x)
                                    for x in f.read().split()[:3]]
        except OSError:
            pass
        try:
            mem = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, rest = line.partition(":")
                    if k in ("MemTotal", "MemAvailable"):
                        mem[k] = int(rest.split()[0]) * 1024
            stats["mem_total_bytes"] = mem.get("MemTotal")
            stats["mem_available_bytes"] = mem.get("MemAvailable")
        except OSError:
            pass
        workers = []
        for handle in self.workers.values():
            entry = {"worker_id": handle.worker_id.hex(),
                     "pid": handle.pid, "state": handle.state,
                     "job": handle.job_hex}
            try:
                with open(f"/proc/{handle.pid}/statm") as f:
                    pages = int(f.read().split()[1])
                entry["rss_bytes"] = pages * os.sysconf("SC_PAGESIZE")
            except (OSError, ValueError, IndexError):
                pass
            workers.append(entry)
        stats["workers"] = workers
        # Owner-shard rows are NOT fanned out here: workers auto-resolve
        # to 1 shard (the sharded fan-in side is the DRIVER, served by
        # /api/shards -> state.shard_summary), and a per-poll RPC to
        # every worker would tax node-stats for rows nobody renders.
        # Per-worker stats stay one `get_shard_stats` call away.
        stats["num_leases"] = len(self.leases)
        stats["resources_total"] = self.resources.total.to_dict()
        stats["resources_available"] = self.resources.available.to_dict()
        return stats

    async def handle_return_worker(self, lease_id: int,
                                   dispose: bool = False):
        entry = self.leases.get(lease_id)
        if entry and dispose:
            handle = self.workers.get(entry[0])
            if handle is not None:
                died = False
                if not CONFIG.no_log_plane and handle.proc is not None \
                        and handle.state != "DEAD":
                    # The usual dispose reason is a worker that died
                    # underneath its caller (the failed push races our
                    # liveness sweep). Give the kernel a short grace to
                    # reap — poll() flips within ~50ms of a SIGKILL —
                    # so a real death takes the postmortem/report path
                    # (the crashing caller is about to ask the GCS for
                    # this worker's last words); a healthy disposal
                    # falls through to the plain kill.
                    deadline = time.monotonic() + 0.5
                    while True:
                        died = handle.proc.poll() is not None
                        if died or time.monotonic() >= deadline:
                            break
                        await asyncio.sleep(0.05)
                if died and handle.state != "DEAD":
                    await self._on_worker_death(handle)
                elif handle.state != "DEAD":
                    self._kill_worker(handle)
                # state == DEAD: the liveness sweep owns this death —
                # killing/retiring here would yank the ring from under
                # its in-flight postmortem
        self._release_lease(lease_id)
        return True

    async def handle_cancel_lease_by_task(self, task_hex: str):
        """Drop a queued lease request for a cancelled task so it stops
        competing for resources (and never cold-starts a worker)."""
        for req in list(self.queued):
            if req.spec_meta.get("task_hex") == task_hex:
                if not req.future.done():
                    req.future.set_result({"canceled": True})
                self.queued.remove(req)
        return True

    async def handle_cancel_lease(self, lease_id: int):
        for req in list(self.queued):
            if req.lease_id == lease_id and not req.future.done():
                req.future.set_result({"rejected": True, "canceled": True})
                self.queued.remove(req)
        return True

    # ------------------------------------------------------------------
    # graceful drain (rolling upgrades / elastic scale-in; reference:
    # node_manager.cc HandleDrainRaylet + the autoscaler drain protocol)
    # ------------------------------------------------------------------

    def _begin_drain(self, reason: str = ""):
        if self._draining:
            return
        self._draining = True
        self._drain_reason = reason or "drain requested"
        logger.warning("raylet %s draining: %s", self.node_id[:12],
                       self._drain_reason)
        # Resolve every queued request NOW: spill it to a healthy node
        # or reject it with the draining marker — drain convergence
        # must not wait on requests this node will never grant.
        queued, self.queued = self.queued, []
        for req in queued:
            if req.future.done():
                continue
            spill = self._pick_spillback(req)
            if spill is not None:
                req.future.set_result({"spillback_to": spill})
            else:
                req.future.set_result(
                    {"rejected": True, "draining": True,
                     "error": "node is draining"})
        self._update_metrics()

    def _cancel_drain(self):
        if not self._draining:
            return
        logger.warning("raylet %s drain canceled", self.node_id[:12])
        self._draining = False
        self._drain_reason = ""
        self._update_metrics()
        self._pump_queue()

    async def handle_drain_self(self, phase: str = "all",
                                timeout_s: Optional[float] = None,
                                exit_process: bool = False,
                                reason: str = ""):
        """GCS-coordinated graceful drain of this raylet.

        ``phase="fence"`` raises the fence and returns immediately (the
        coordinator then migrates actors off this node);
        ``phase="wait"`` blocks until every in-flight lease is returned
        — idle leases come home via the owners' fairness-rotation /
        idle-cleaner ticks within ~lease_idle_timeout_s — or the
        deadline passes, at which point stragglers get postmortem-
        tagged SIGKILLs (kill_reason="drain_timeout" →
        DRAIN_TIMEOUT_KILLED), never a hang. ``exit_process=True`` asks
        a standalone raylet main to exit clean after replying.
        ``phase="cancel"`` lowers the fence and re-pumps the queue."""
        if phase == "cancel":
            self._cancel_drain()
            return {"draining": False}
        self._begin_drain(reason)
        if phase == "fence":
            return {"draining": True, "leases": len(self.leases),
                    "workers": len(self.workers)}
        budget = timeout_s if timeout_s is not None \
            else CONFIG.drain_timeout_s
        t0 = time.monotonic()
        deadline = t0 + budget
        while self.leases and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        killed: List[str] = []
        if self.leases:
            # Stragglers: killed, tagged so the postmortem taxonomy
            # reports DRAIN_TIMEOUT_KILLED with certainty rather than
            # guessing at a foreign SIGKILL.
            for worker_id, _demand, _pg in list(self.leases.values()):
                handle = self.workers.get(worker_id)
                if handle is None or handle.state == "DEAD":
                    continue
                logger.warning(
                    "drain deadline (%.1fs): killing straggler worker "
                    "%s (pid %s)", budget, handle.worker_id.hex()[:12],
                    handle.pid)
                handle.kill_reason = "drain_timeout"
                killed.append(handle.worker_id.hex())
                if handle.proc is not None:
                    try:
                        handle.proc.kill()
                    except Exception:
                        logger.debug("drain kill of pid %s failed",
                                     handle.pid, exc_info=True)
                else:
                    self._kill_worker(handle)
            # The death path (liveness sweep / dispose) releases their
            # leases and files the postmortems; wait briefly for the
            # fold, then force-release whatever is left.
            grace = time.monotonic() + 5.0
            while self.leases and time.monotonic() < grace:
                await asyncio.sleep(0.05)
            for lease_id in list(self.leases):
                self._release_lease(lease_id)
        # Idle/starting workers are never reused post-drain: reap them.
        for handle in list(self.workers.values()):
            if handle.state in ("IDLE", "STARTING") \
                    and handle.lease_id is None:
                self._kill_worker(handle)
        elapsed = time.monotonic() - t0
        from .runtime_metrics import runtime_metrics
        metrics = runtime_metrics()
        tags = {"node": str(self.node_index)}
        metrics.drain_latency.observe(elapsed, tags=tags)
        metrics.drains_completed.inc(tags=dict(
            tags, outcome="timeout" if killed else "clean"))
        self._gcs_event(
            "NODE_DRAINED",
            f"node {self.node_id[:12]} drained in {elapsed:.2f}s"
            + (f" ({len(killed)} stragglers killed)" if killed else ""),
            severity="WARNING" if killed else "INFO",
            elapsed_s=elapsed, stragglers_killed=killed,
            will_exit=exit_process)
        if exit_process and self.exit_requested is not None:
            # Reply first; a standalone raylet main (raylet_main.py)
            # wakes on the event and exits clean. In-process raylets
            # (local mode / the embedded head) just stay fenced.
            asyncio.get_running_loop().call_later(
                0.2, self.exit_requested.set)
        return {"drained": True, "elapsed_s": elapsed,
                "stragglers_killed": killed,
                "timed_out": bool(killed), "exiting": exit_process}

    # ------------------------------------------------------------------
    # placement group bundles (two-phase commit, raylet side)
    # ------------------------------------------------------------------

    async def handle_prepare_bundle(self, pg_id: PlacementGroupID,
                                    bundle_index: int,
                                    resources: Dict[str, float]):
        demand = ResourceSet(resources)
        key = (pg_id, bundle_index)
        if key in self.bundles:
            return True
        if not self.resources.try_allocate(demand):
            return False
        self.bundles[key] = BundleAccount(resources=demand, available=demand)
        return True

    async def handle_commit_bundle(self, pg_id: PlacementGroupID,
                                   bundle_index: int):
        account = self.bundles.get((pg_id, bundle_index))
        if account is None:
            return False
        account.committed = True
        self._pump_queue()
        return True

    async def handle_cancel_bundle(self, pg_id: PlacementGroupID,
                                   bundle_index: int):
        account = self.bundles.pop((pg_id, bundle_index), None)
        if account is not None:
            self.resources.release(account.resources)
            self._pump_queue()
        return True

    # ------------------------------------------------------------------
    # local object manager (reference: local_object_manager.cc + plasma
    # eviction + pull/push managers)
    # ------------------------------------------------------------------

    async def handle_seal_object(self, object_hex: str, size: int,
                                 owner_address: Optional[Address]):
        self.objects[object_hex] = ObjectEntry(size=size,
                                               last_access=time.monotonic())
        self.store_used += size
        gcs = self.clients.get(self.gcs_address)
        aio.spawn(gcs.call(
            "add_object_location", object_hex=object_hex,
            node_id=self.node_id, size=size, owner_address=owner_address,
            timeout=10), what="add_object_location")
        if self.store_used > self.capacity * CONFIG.object_spilling_threshold:
            aio.spawn(self._evict_until_under(), what="evict_until_under")
        return True

    async def _evict_until_under(self):
        target = self.capacity * CONFIG.object_spilling_threshold * 0.8
        victims = sorted(
            ((h, e) for h, e in self.objects.items() if e.pinned == 0),
            key=lambda kv: kv[1].last_access)
        gcs = self.clients.get(self.gcs_address)
        from .runtime_metrics import runtime_metrics
        metrics = runtime_metrics()
        tags = {"node": str(self.node_index)}
        for object_hex, entry in victims:
            if self.store_used <= target:
                break
            try:
                spill_t = time.monotonic()
                oid = ObjectID.from_hex(object_hex)
                if self.spill_storage is not None:
                    # Cloud spilling (reference: external_storage.py:398):
                    # ship the bytes through fsspec, free the local copy.
                    data = self.plasma.read_bytes(oid)
                    if data is None:
                        raise FileNotFoundError(object_hex)
                    path = await asyncio.get_running_loop().run_in_executor(
                        None, self.spill_storage.put, object_hex, data)
                    self.plasma.delete(oid)
                else:
                    path = self.plasma.spill_to(oid, self.spill_dir)
                entry.spilled_path = path
                self.store_used -= entry.size
                del self.objects[object_hex]
                self.spilled_objects[object_hex] = entry.size
                self.spilled_bytes += entry.size
                self.spilled_bytes_total += entry.size
                self.spill_count += 1
                metrics.store_spilled_total.inc(entry.size, tags=tags)
                metrics.store_spill_latency.observe(
                    time.monotonic() - spill_t, tags=tags)
                self._gcs_event(
                    "SPILL",
                    f"spilled {object_hex[:12]} ({entry.size} bytes)",
                    object_id=object_hex, size=entry.size, path=path)
                await gcs.call("add_spilled_location",
                               object_hex=object_hex, path=path, timeout=10)
                await gcs.call("remove_object_location",
                               object_hex=object_hex, node_id=self.node_id,
                               timeout=10)
            except FileNotFoundError:
                self.objects.pop(object_hex, None)
            except Exception:
                logger.exception("spill of %s failed", object_hex[:12])

    async def handle_pull_object(self, object_hex: str):
        """Ensure the object is locally readable; used by workers on get()."""
        oid = ObjectID.from_hex(object_hex)
        entry = self.objects.get(object_hex)
        if entry is not None:
            entry.last_access = time.monotonic()
            return {"ok": True}
        # Deduplicate concurrent pulls.
        pending = self._pulls.get(object_hex)
        if pending is not None:
            return await pending
        fut = asyncio.get_running_loop().create_future()
        self._pulls[object_hex] = fut
        try:
            result = await self._pull_object(oid, object_hex)
            if not fut.done():
                fut.set_result(result)
            return result
        except Exception as e:
            if not fut.done():
                fut.set_exception(e)
            raise
        finally:
            self._pulls.pop(object_hex, None)

    async def _pull_object(self, oid: ObjectID, object_hex: str):
        # A push of this object may be assembling right now — it owns the
        # store's tmp file, so wait for it rather than racing the create.
        if object_hex in self._push_assembly:
            deadline = time.monotonic() + 120
            while object_hex in self._push_assembly:
                if time.monotonic() > deadline:
                    break
                await asyncio.sleep(0.05)
            if self.plasma.contains(oid):
                size = self.plasma.size_of(oid)
                self.objects.setdefault(object_hex, ObjectEntry(
                    size=size, last_access=time.monotonic()))
                return {"ok": True}
        gcs = self.clients.get(self.gcs_address)
        info = await gcs.call("get_object_locations", object_hex=object_hex,
                              timeout=10)
        spilled = info.get("spilled")
        if spilled and "://" in spilled and self.spill_storage is not None:
            restore_t = time.monotonic()
            data = await asyncio.get_running_loop().run_in_executor(
                None, self.spill_storage.get, spilled)
            if data is not None:
                self.plasma.write_bytes(oid, data)
                size = len(data)
                self.objects[object_hex] = ObjectEntry(
                    size=size, last_access=time.monotonic())
                self.store_used += size
                self._record_restore(object_hex, size,
                                     time.monotonic() - restore_t)
                await gcs.call("add_object_location",
                               object_hex=object_hex,
                               node_id=self.node_id,
                               size=info.get("size", size),
                               owner_address=info.get("owner"), timeout=10)
                return {"ok": True}
        if spilled and "://" not in spilled and os.path.exists(spilled):
            restore_t = time.monotonic()
            self.plasma.restore_from(oid, spilled)
            size = self.plasma.size_of(oid)
            self.objects[object_hex] = ObjectEntry(
                size=size, last_access=time.monotonic())
            self.store_used += size
            self._record_restore(object_hex, size,
                                 time.monotonic() - restore_t)
            await gcs.call("add_object_location", object_hex=object_hex,
                           node_id=self.node_id, size=info.get("size", size),
                           owner_address=info.get("owner"), timeout=10)
            return {"ok": True}
        # Randomize replica choice so a broadcast storm spreads across the
        # nodes that already hold a copy instead of funnelling into the
        # first-listed (usually the origin) node.
        candidates = list(info.get("nodes", []))
        random.shuffle(candidates)
        if self.node_id in info.get("nodes", []):
            candidates.insert(0, self.node_id)
        for node_id in candidates:
            if node_id == self.node_id:
                if self.plasma.contains(oid):
                    size = self.plasma.size_of(oid)
                    self.objects[object_hex] = ObjectEntry(
                        size=size, last_access=time.monotonic())
                    self.store_used += size
                    return {"ok": True}
                continue
            addr = self.node_addresses.get(node_id)
            if addr is None:
                nodes = await gcs.call("get_all_nodes", timeout=10)
                for n in nodes:
                    self.node_addresses[n["node_id"]] = tuple(n["address"])
                addr = self.node_addresses.get(node_id)
            if addr is None:
                continue
            try:
                await self._fetch_from(addr, oid, object_hex)
                return {"ok": True}
            except Exception as e:
                logger.warning("pull of %s from %s failed: %s",
                               object_hex[:12], node_id[:12], e)
        return {"ok": False, "error": "no reachable copy"}

    async def _fetch_from(self, addr: Address, oid: ObjectID,
                          object_hex: str):
        peer = self.clients.get(addr)
        meta = await peer.call("object_info", object_hex=object_hex,
                               timeout=30)
        size = meta["size"]
        chunk = CONFIG.object_store_chunk_bytes
        buf = self.plasma.create(oid, size)
        try:
            # Windowed parallel chunk fetch (reference: pull_manager.cc
            # keeps several chunk requests in flight): overlaps the
            # peer's read+serialize with our write.
            sem = asyncio.Semaphore(4)

            async def _one(offset: int, n: int):
                async with sem:
                    data = await peer.call(
                        "fetch_chunk", object_hex=object_hex,
                        offset=offset, length=n, timeout=60)
                    buf[offset:offset + len(data)] = data
            tasks = [asyncio.ensure_future(
                _one(off, min(chunk, size - off)))
                for off in range(0, size, chunk)]
            try:
                await asyncio.gather(*tasks)
            except BaseException:
                for t in tasks:  # stop siblings before releasing buf
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                raise
        except Exception:
            buf.release()
            self.plasma.abort(oid)
            raise
        buf.release()
        self.plasma.seal(oid)
        self.objects[object_hex] = ObjectEntry(size=size,
                                               last_access=time.monotonic())
        self.store_used += size
        gcs = self.clients.get(self.gcs_address)
        await gcs.call("add_object_location", object_hex=object_hex,
                       node_id=self.node_id, size=size,
                       owner_address=None, timeout=10)

    def _record_restore(self, object_hex: str, size: int, latency_s: float):
        """Fold one spill-restore into the accounting + metrics + event
        log (both the cloud and the local-disk restore paths land here)."""
        self.restored_bytes_total += size
        self.restore_count += 1
        spilled_size = self.spilled_objects.pop(object_hex, None)
        if spilled_size is not None:
            self.spilled_bytes -= spilled_size
        from .runtime_metrics import runtime_metrics
        tags = {"node": str(self.node_index)}
        runtime_metrics().store_restored_total.inc(size, tags=tags)
        runtime_metrics().store_restore_latency.observe(latency_s,
                                                        tags=tags)
        self._gcs_event("RESTORE",
                        f"restored {object_hex[:12]} ({size} bytes)",
                        object_id=object_hex, size=size)

    async def handle_object_info(self, object_hex: str):
        oid = ObjectID.from_hex(object_hex)
        entry = self.objects.get(object_hex)
        if entry is None or not self.plasma.contains(oid):
            raise KeyError(f"object {object_hex[:12]} not local")
        return {"size": self.plasma.size_of(oid)}

    async def handle_fetch_chunk(self, object_hex: str, offset: int,
                                 length: int):
        oid = ObjectID.from_hex(object_hex)
        view = self.plasma.map_read(oid)
        if view is None:
            raise KeyError(f"object {object_hex[:12]} not local")
        try:
            return bytes(view[offset:offset + length])
        finally:
            view.release()

    # ------------------------------------------------------------------
    # push-based broadcast (reference: src/ray/object_manager/
    # push_manager.cc — owner-initiated chunked pushes; here arranged as
    # a binary forwarding tree so source egress is O(2N) regardless of
    # the receiver count, and every tree level streams in parallel)
    # ------------------------------------------------------------------

    @staticmethod
    def _tree_split(nodes: List) -> List[List]:
        """Binary forwarding-tree split: two contiguous halves, each led
        by its first element."""
        mid = (len(nodes) + 1) // 2
        return [g for g in (nodes[:mid], nodes[mid:]) if g]

    async def handle_profile_worker(self, pid: int, kind: str = "pystack",
                                    duration_s: float = 1.0):
        """Forward a profile capture to the worker with `pid` on this
        node (reference: reporter agent routing profile requests)."""
        for handle in self.workers.values():
            if handle.pid == pid and handle.address is not None:
                client = self.clients.get(handle.address)
                return await client.call(
                    "capture_profile", kind=kind, duration_s=duration_s,
                    timeout=duration_s + 60)
        return {"error": f"no worker with pid {pid} on this node"}

    # ------------------------------------------------------------------
    # continuous profiling plane (the get_memory_report fan-out pattern:
    # the raylet IS the node agent — one RPC profiles the whole node)
    # ------------------------------------------------------------------

    def _profiling_targets(self) -> List[WorkerHandle]:
        return [h for h in self.workers.values()
                if h.address is not None and h.state != "DEAD"]

    async def handle_start_profiling(self, hz: Optional[float] = None,
                                     ring_size: Optional[int] = None):
        from . import profiler
        return profiler.start_profiling(hz=hz, ring_size=ring_size)

    async def handle_stop_profiling(self):
        from . import profiler
        return profiler.stop_profiling()

    async def handle_get_profile(self, clear: bool = True,
                                 stop: bool = False):
        from . import profiler
        report = profiler.get_profile(clear=clear, stop=stop)
        report["node_id"] = self.node_id
        report["node_index"] = self.node_index
        report["component"] = "raylet"
        return report

    async def handle_profile_node(self, duration_s: float = 2.0,
                                  hz: Optional[float] = None):
        """Sample every process on this node for `duration_s`: the
        raylet's own process plus all live workers, started and
        collected CONCURRENTLY. A worker that refuses (kill switch) or
        dies mid-capture contributes an error row, not a gap. Samplers
        this call started are stopped after collection; an
        already-running (continuous-mode) sampler is left running."""
        from . import profiler
        duration_s = min(float(duration_s), 60.0)
        hz = hz or CONFIG.profiler_hz
        own_start = profiler.start_profiling(hz=hz)
        targets = self._profiling_targets()

        async def _start(handle):
            try:
                return await self.clients.get(handle.address).call(
                    "start_profiling", hz=hz, timeout=10)
            except Exception as e:  # noqa: BLE001 — surfaced as a row
                return {"error": str(e)}

        starts = list(await asyncio.gather(
            *(_start(h) for h in targets))) if targets else []

        # A continuous-mode sampler that was already running has a ring
        # full of pre-window backlog — drain (discard) it now so the
        # post-window collection holds only this capture's samples.
        async def _predrain(handle):
            try:
                await self.clients.get(handle.address).call(
                    "get_profile", clear=True, stop=False, timeout=10)
            except Exception:  # noqa: BLE001 — collect will surface it
                logger.debug("profiler pre-drain failed", exc_info=True)

        stale = [h for h, s in zip(targets, starts)
                 if s.get("already_running")]
        if own_start.get("already_running"):
            profiler.get_profile(clear=True)
        if stale:
            await asyncio.gather(*(_predrain(h) for h in stale))
        await asyncio.sleep(duration_s)
        reports: List[Dict[str, Any]] = []
        errors: List[Dict[str, Any]] = []

        async def _collect(handle, started):
            if started.get("error") or not started.get("running"):
                errors.append({
                    "node_id": self.node_id, "pid": handle.pid,
                    "worker_id": handle.worker_id.hex(),
                    "error": started.get("error", "sampler not running")})
                return
            try:
                reports.append(await asyncio.wait_for(
                    self.clients.get(handle.address).call(
                        "get_profile", clear=True,
                        stop=not started.get("already_running"),
                        timeout=15), 20))
            except Exception as e:  # noqa: BLE001 — surfaced as a row
                errors.append({
                    "node_id": self.node_id, "pid": handle.pid,
                    "worker_id": handle.worker_id.hex(),
                    "error": str(e)})

        if targets:
            await asyncio.gather(
                *(_collect(h, s) for h, s in zip(targets, starts)))
        if own_start.get("running"):
            own = profiler.get_profile(
                clear=True, stop=not own_start.get("already_running"))
            own.update(node_id=self.node_id, node_index=self.node_index,
                       component="raylet")
            reports.append(own)
        else:
            errors.append({"node_id": self.node_id, "pid": os.getpid(),
                           "component": "raylet",
                           "error": own_start.get(
                               "error", "sampler not running")})
        return {"node_id": self.node_id, "node_index": self.node_index,
                "hz": hz, "reports": reports, "errors": errors}

    async def handle_profiling_status(self):
        """Sampler status for every process on this node."""
        from . import profiler
        rows = [dict(profiler.profiling_status(), component="raylet",
                     node_id=self.node_id)]
        targets = self._profiling_targets()

        async def _one(handle):
            try:
                rows.append(await asyncio.wait_for(
                    self.clients.get(handle.address).call(
                        "profiling_status", timeout=10), 15))
            except Exception as e:  # noqa: BLE001 — surfaced as a row
                rows.append({"node_id": self.node_id, "pid": handle.pid,
                             "error": str(e)})
        if targets:
            await asyncio.gather(*(_one(h) for h in targets))
        return {"node_id": self.node_id, "node_index": self.node_index,
                "processes": rows}

    async def handle_stack_dump_node(self):
        """One-shot stack dump of every process on this node (the
        `cli stack` backend): the raylet's own threads plus every live
        worker's full dump, fetched concurrently."""
        from . import profiler
        rows: List[Dict[str, Any]] = [{
            "node_id": self.node_id, "node_index": self.node_index,
            "pid": os.getpid(), "component": "raylet",
            "text": profiler.stack_dump_text(),
        }]
        targets = self._profiling_targets()

        async def _one(handle):
            try:
                text = await asyncio.wait_for(
                    self.clients.get(handle.address).call(
                        "dump_stacks", quiet=True, timeout=15), 20)
                rows.append({
                    "node_id": self.node_id,
                    "node_index": self.node_index,
                    "pid": handle.pid, "component": "worker",
                    "worker_id": handle.worker_id.hex(),
                    "text": text if isinstance(text, str) else "",
                })
            except Exception as e:  # noqa: BLE001 — surfaced as a row
                rows.append({"node_id": self.node_id, "pid": handle.pid,
                             "worker_id": handle.worker_id.hex(),
                             "error": str(e)})
        if targets:
            await asyncio.gather(*(_one(h) for h in targets))
        return rows

    async def handle_push_object(self, object_hex: str,
                                 target_node_ids: Optional[List[str]] = None):
        """Push a locally-held object to `target_node_ids` (default: every
        other alive node). Returns when all receivers have sealed it."""
        oid = ObjectID.from_hex(object_hex)
        if not self.plasma.contains(oid):
            return {"ok": False, "error": "object not local to this node"}
        size = self.plasma.size_of(oid)
        if target_node_ids is None:
            target_node_ids = [nid for nid in self.cluster_view
                               if nid != self.node_id]
        addrs = []
        for nid in target_node_ids:
            if nid == self.node_id:
                continue
            addr = self.node_addresses.get(nid)
            if addr is not None:
                addrs.append(tuple(addr))
        if not addrs:
            return {"ok": True, "receivers": 0}
        await self._push_stream(oid, object_hex, size, addrs)
        return {"ok": True, "receivers": len(addrs)}

    async def _push_stream(self, oid, object_hex: str, size: int,
                           addrs: List[Address]):
        """Stream chunks to the two tree children (each forwarding to its
        own subtree), windowed for pipelining."""
        groups = self._tree_split(addrs)
        chunk = CONFIG.object_store_chunk_bytes
        view = self.plasma.map_read(oid)
        if view is None:
            raise KeyError(f"object {object_hex[:12]} vanished mid-push")
        sem = asyncio.Semaphore(4)

        async def _send(group, offset, n):
            peer = self.clients.get(group[0])
            async with sem:
                data = bytes(view[offset:offset + n])
                await peer.call(
                    "push_chunk", object_hex=object_hex, size=size,
                    offset=offset, data=data,
                    forward_to=list(group[1:]), timeout=120)
        tasks = [asyncio.ensure_future(
            _send(group, off, min(chunk, size - off)))
            for group in groups for off in range(0, size, chunk)]
        try:
            await asyncio.gather(*tasks)
        except BaseException:
            # siblings must stop touching the view before we release it
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        finally:
            view.release()

    async def handle_push_chunk(self, object_hex: str, size: int,
                                offset: int, data: bytes,
                                forward_to: List):
        """Receive one pushed chunk, forward it down the subtree, seal on
        completion. Replies only after local write + forward, so the
        sender's window regulates the whole pipeline. Forwarding happens
        even when the local copy is skipped (already held, or a pull of
        the same object is in flight) — the subtree must still be fed."""
        oid = ObjectID.from_hex(object_hex)
        skip_local = object_hex in self._pulls  # pull owns the tmp file
        assy = None
        if not skip_local:
            assy = self._push_assembly.get(object_hex)
            if assy is None:
                if self.plasma.contains(oid):
                    skip_local = True
                else:
                    buf = self.plasma.create(oid, size)
                    assy = {"buf": buf, "received": 0, "size": size,
                            "offsets": set(), "t": time.monotonic()}
                    self._push_assembly[object_hex] = assy
        if assy is not None:
            if offset not in assy["offsets"]:  # dedup concurrent pushes
                assy["buf"][offset:offset + len(data)] = data
                assy["received"] += len(data)
                assy["offsets"].add(offset)
            assy["t"] = time.monotonic()
        if forward_to:
            await asyncio.gather(*[
                self.clients.get(tuple(g[0])).call(
                    "push_chunk", object_hex=object_hex, size=size,
                    offset=offset, data=data, forward_to=list(g[1:]),
                    timeout=120)
                for g in self._tree_split(forward_to)])
        if assy is None:
            return {"ok": True, "dup": True}
        # Single-seal guard: concurrent chunk handlers resume from their
        # forwarding awaits after completion; only the first may seal.
        if assy["received"] >= size and not assy.get("sealed"):
            assy["sealed"] = True
            self._push_assembly.pop(object_hex, None)
            assy["buf"].release()
            self.plasma.seal(oid)
            self.objects[object_hex] = ObjectEntry(
                size=size, last_access=time.monotonic())
            self.store_used += size
            gcs = self.clients.get(self.gcs_address)
            aio.spawn(gcs.call(
                "add_object_location", object_hex=object_hex,
                node_id=self.node_id, size=size, owner_address=None,
                timeout=10), what="add_object_location")
        return {"ok": True}

    async def handle_free_objects(self, object_hexes: List[str]):
        for object_hex in object_hexes:
            entry = self.objects.pop(object_hex, None)
            if entry is not None:
                self.store_used -= entry.size
            spilled_size = self.spilled_objects.pop(object_hex, None)
            if spilled_size is not None:
                self.spilled_bytes -= spilled_size
            self.plasma.delete(ObjectID.from_hex(object_hex))
        return True

    async def handle_pin_object(self, object_hex: str, delta: int = 1):
        entry = self.objects.get(object_hex)
        if entry is not None:
            entry.pinned = max(0, entry.pinned + delta)
        return entry is not None

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------

    async def handle_ping(self):
        return "pong"

    # -- chaos harness (cli chaos / tests) -----------------------------

    async def handle_set_chaos(self, spec: str = "", seed: int = 0,
                               schedule: Optional[str] = None):
        from . import chaos
        return await chaos.handle_set_chaos(spec=spec, seed=seed,
                                            schedule=schedule)

    async def handle_chaos_kill_worker(self, worker_hex: str = "",
                                       pid: int = 0):
        """SIGKILL one of this raylet's workers (`cli chaos kill-worker`
        / tests): by worker hex or raw pid. Gated like kill-gcs."""
        if not CONFIG.chaos_allow_kill:
            raise PermissionError(
                "chaos kill refused: set RTPU_CHAOS_ALLOW_KILL=1 on the "
                "raylet process to allow it")
        from . import chaos
        if worker_hex:
            handle = next((h for h in self.workers.values()
                           if h.worker_id.hex().startswith(worker_hex)),
                          None)
            if handle is None:
                return False
            pid = handle.pid
        if not pid:
            return False
        return chaos.kill_pid(pid)

    async def handle_get_memory_report(self, limit: int = 10_000,
                                       include_workers: bool = True):
        """Node memory report: raylet store accounting (capacity,
        resident/pinned/spilled bytes, per-object pin counts + LRU age)
        plus every local worker's owner-side reference report, fetched
        concurrently (reference: LocalObjectManager::RecordMetrics +
        node_manager's FormatGlobalMemoryInfo fan-in)."""
        now = time.monotonic()
        rows = []
        for object_hex, entry in self.objects.items():
            rows.append({"object_id": object_hex, "size": entry.size,
                         "pinned": entry.pinned,
                         "age_s": now - entry.last_access,
                         "spilled": False})
            if len(rows) >= limit:
                break
        for object_hex, size in self.spilled_objects.items():
            if len(rows) >= limit:
                break
            rows.append({"object_id": object_hex, "size": size,
                         "pinned": 0, "age_s": None, "spilled": True})
        report = {
            "node_id": self.node_id,
            "node_index": self.node_index,
            "store": {
                "capacity": self.capacity,
                "used_bytes": self.store_used,
                "pinned_bytes": sum(e.size for e in self.objects.values()
                                    if e.pinned > 0),
                "num_objects": len(self.objects),
                "spilled_bytes": self.spilled_bytes,
                "num_spilled": len(self.spilled_objects),
                "spilled_bytes_total": self.spilled_bytes_total,
                "restored_bytes_total": self.restored_bytes_total,
                "spill_count": self.spill_count,
                "restore_count": self.restore_count,
            },
            "mem_pressure": self._mem_pressure,
            "objects": rows,
            "workers": [],
        }
        if include_workers:
            targets = [h for h in self.workers.values()
                       if h.address is not None and h.state != "DEAD"]

            async def _one(handle):
                try:
                    return await asyncio.wait_for(
                        self.clients.get(handle.address).call(
                            "get_memory_report", limit=limit,
                            timeout=10), 15)
                except Exception as e:  # noqa: BLE001 — report the gap
                    return {"worker_id": handle.worker_id.hex(),
                            "node_id": self.node_id, "pid": handle.pid,
                            "error": str(e)}
            if targets:
                report["workers"] = list(await asyncio.gather(
                    *(_one(h) for h in targets)))
        return report

    async def handle_get_logs(self, job: Optional[str] = None,
                              task: Optional[str] = None,
                              actor: Optional[str] = None,
                              level: Optional[str] = None,
                              grep: Optional[str] = None,
                              tail: Optional[int] = None,
                              since: Optional[Dict[str, int]] = None,
                              limit: int = 1000,
                              pid: Optional[int] = None,
                              include_dead: bool = True):
        """Query this node's worker log rings (live + retained dead).
        Filters: job/task/actor hex (prefix for ids), min `level`,
        `grep` regex, `tail`-N after the merge; `since` is the cursor
        dict a previous reply returned ({worker_hex: seq}) — pass it
        back to follow (only lines newer than the cursor return)."""
        since = since or {}
        limit = max(1, min(int(limit), 10_000))
        rows: List[Dict[str, Any]] = []
        cursors: Dict[str, int] = {}
        matched_counts: Dict[str, int] = {}
        scan_complete: Dict[str, int] = {}  # worker -> seq scanned to
        dropped = 0
        for ring in self.log_rings.all_rings():
            if not include_dead and not ring.alive:
                continue
            if pid is not None and ring.pid != pid:
                continue
            since_seq = int(since.get(ring.worker_hex, 0))
            cursors[ring.worker_hex] = since_seq
            # end-of-scan seq is captured BEFORE the query: an append
            # racing in between must not be fast-forwarded over (it
            # lands at a seq above this bound and the next poll gets it)
            end_seq = ring.next_seq
            matched = ring.query(
                job=job, task=task, actor=actor, level=level, grep=grep,
                since_seq=since_seq, limit=limit)
            matched_counts[ring.worker_hex] = len(matched)
            if len(matched) < limit:
                # the scan reached the ring's end — everything up to
                # end_seq was either matched or filtered out
                scan_complete[ring.worker_hex] = end_seq
            dropped += ring.dropped
            rows.extend(matched)
        rows.sort(key=lambda e: (e["ts"], e["seq"]))
        if tail:
            rows = rows[-max(1, int(tail)):]
        rows = rows[:limit]
        # Follow-cursor contract: advance a worker's cursor only past
        # lines actually RETURNED, or past fully scanned-and-filtered
        # ranges. Truncation (per-ring limit, the global limit, or
        # tail) must never fast-forward a follower over lines it was
        # not handed. Per ring, ts and seq are both monotonic, so
        # global-limit truncation drops a ring's HIGHEST seqs (safe to
        # cursor at the returned max) while tail drops its lowest
        # (skipping those is exactly what tail asks for).
        returned: Dict[str, int] = {}
        for r in rows:
            w = r["worker_id"]
            returned[w] = returned.get(w, 0) + 1
            if r["seq"] > cursors.get(w, 0):
                cursors[w] = r["seq"]
        for w, end_seq in scan_complete.items():
            if returned.get(w, 0) == matched_counts.get(w, 0):
                # every matched line of this ring was returned and the
                # scan was complete: skip the filtered-out remainder
                cursors[w] = max(cursors[w], end_seq)
        rows = [dict(r, node_id=self.node_id,
                     node_index=self.node_index) for r in rows]
        return {"node_id": self.node_id, "node_index": self.node_index,
                "lines": rows, "cursors": cursors, "dropped": dropped,
                "disabled": CONFIG.no_log_plane}

    async def handle_list_logs(self):
        """Ring inventory for this node: one meta row per worker ring
        (live and retained-dead) — line/drop/byte counts and the
        first/last timestamps, no line payloads."""
        return {"node_id": self.node_id, "node_index": self.node_index,
                "disabled": CONFIG.no_log_plane,
                "pub_dropped_lines": self._log_pub_window.dropped_lines,
                "rings": [dict(r.meta(), node_id=self.node_id,
                               node_index=self.node_index)
                          for r in self.log_rings.all_rings()]}

    async def handle_get_accel_report(self, include_workers: bool = True):
        """Node accelerator report: every local worker's device/compile/
        step telemetry, fetched concurrently (the get_memory_report
        fan-out pattern — the raylet IS the node agent). The raylet's
        own process never initializes jax, so its row is just the node
        wrapper."""
        report: Dict[str, Any] = {
            "node_id": self.node_id,
            "node_index": self.node_index,
            "workers": [],
        }
        if include_workers:
            targets = [h for h in self.workers.values()
                       if h.address is not None and h.state != "DEAD"]

            async def _one(handle):
                try:
                    return await asyncio.wait_for(
                        self.clients.get(handle.address).call(
                            "get_accel_report", timeout=10), 15)
                except Exception as e:  # noqa: BLE001 — report the gap
                    return {"worker_id": handle.worker_id.hex(),
                            "node_id": self.node_id, "pid": handle.pid,
                            "error": str(e)}
            if targets:
                report["workers"] = list(await asyncio.gather(
                    *(_one(h) for h in targets)))
        return report

    async def handle_get_rpc_stats(self):
        """Transport-observatory introspection for this raylet process
        (state.rpc_summary() merges these with the driver/worker rows)."""
        from . import rpc_metrics
        stats = rpc_metrics.local_stats()
        stats["node_id"] = self.node_id
        stats["mode"] = "raylet"
        return stats

    async def handle_get_node_stats(self):
        return {
            "node_id": self.node_id,
            "resources_total": self.resources.total.to_dict(),
            "resources_available": self.resources.available.to_dict(),
            "num_workers": len(self.workers),
            "num_leases": len(self.leases),
            "num_queued_leases": len(self.queued),
            "draining": self._draining,
            "queue_ages": self._queue_ages(),
            "object_store_used": self.store_used,
            "object_store_capacity": self.capacity,
            "num_objects": len(self.objects),
            "labels": self.labels,
            "workers": [
                {"worker_id": h.worker_id.hex(), "pid": h.pid,
                 "state": h.state,
                 "is_actor_worker": h.is_actor_worker}
                for h in self.workers.values()
            ],
        }
