"""Standalone raylet process entrypoint (reference: raylet/main.cc via
`ray start`). Used by cluster_utils.Cluster.add_node and the CLI to run
worker nodes as real separate processes."""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import signal
import sys


def main(argv=None):
    # Before any ray_tpu lock is constructed in this process.
    from .lint import sanitizer as _sanitizer
    _sanitizer.enable_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--session", required=True)
    parser.add_argument("--node-index", type=int, required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--head", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format=f"[raylet {args.node_index}] %(levelname)s %(name)s: "
               "%(message)s")
    host, port = args.gcs_address.rsplit(":", 1)

    from .raylet import Raylet

    raylet = Raylet(
        session_name=args.session,
        gcs_address=(host, int(port)),
        resources=json.loads(args.resources),
        labels=json.loads(args.labels),
        node_index=args.node_index,
        is_head=args.head,
        object_store_memory=args.object_store_memory or None)

    async def run():
        await raylet.start()
        # readiness protocol line cluster_utils waits on
        print(f"RTPU_RAYLET_READY {raylet.node_id} "  # stdout ok: protocol
              f"{raylet.address[0]}:{raylet.address[1]}", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        # Two exit triggers: a signal, or a completed graceful drain
        # with exit_process=True (the rolling-restart primitive —
        # drain_self replies first, then wakes this event).
        waits = [asyncio.ensure_future(stop.wait()),
                 asyncio.ensure_future(raylet.exit_requested.wait())]
        done, pending = await asyncio.wait(
            waits, return_when=asyncio.FIRST_COMPLETED)
        for fut in pending:
            fut.cancel()
        if raylet.exit_requested.is_set():
            logging.getLogger(__name__).warning(
                "raylet %s exiting clean after drain",
                raylet.node_id[:12])
        await raylet.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
