"""Resource accounting.

Mirrors the reference's model (src/ray/common/scheduling/resource_set.h,
fixed_point.h, scheduling_ids.h): resource quantities are fixed-point
integers (1e-4 granularity) so fractional resources add exactly; resource
names are interned to ints for cheap comparison.

Predefined resources: "CPU", "TPU", "GPU", "memory", "object_store_memory".
Custom resources (e.g. "TPU-v5p-64-head", node labels) are arbitrary strings.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Mapping, Optional

RESOLUTION = 10000  # 1e-4 granularity, same as the reference FixedPoint.


def to_fixed(value: float) -> int:
    return round(value * RESOLUTION)


def from_fixed(value: int) -> float:
    return value / RESOLUTION


class _Interner:
    """string <-> int interning (reference: scheduling_ids.h)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._to_id: Dict[str, int] = {}
        self._to_str: list = []

    def intern(self, name: str) -> int:
        with self._lock:
            rid = self._to_id.get(name)
            if rid is None:
                rid = len(self._to_str)
                self._to_id[name] = rid
                self._to_str.append(name)
            return rid

    def name(self, rid: int) -> str:
        return self._to_str[rid]


RESOURCE_IDS = _Interner()
for _predef in ("CPU", "TPU", "GPU", "memory", "object_store_memory"):
    RESOURCE_IDS.intern(_predef)


class ResourceSet:
    """A bag of named fixed-point resource quantities."""

    __slots__ = ("_amounts",)

    def __init__(self, amounts: Optional[Mapping[str, float]] = None,
                 _fixed: Optional[Dict[int, int]] = None):
        if _fixed is not None:
            self._amounts = {r: q for r, q in _fixed.items() if q != 0}
        else:
            self._amounts = {}
            if amounts:
                for name, qty in amounts.items():
                    fixed = to_fixed(qty)
                    if fixed < 0:
                        raise ValueError(f"negative resource {name}={qty}")
                    if fixed:
                        self._amounts[RESOURCE_IDS.intern(name)] = fixed

    def to_dict(self) -> Dict[str, float]:
        return {RESOURCE_IDS.name(r): from_fixed(q) for r, q in self._amounts.items()}

    def get(self, name: str) -> float:
        return from_fixed(self._amounts.get(RESOURCE_IDS.intern(name), 0))

    def is_empty(self) -> bool:
        return not self._amounts

    def names(self) -> Iterable[str]:
        return [RESOURCE_IDS.name(r) for r in self._amounts]

    def fits(self, available: "ResourceSet") -> bool:
        """True iff every demanded quantity is <= available."""
        avail = available._amounts
        return all(avail.get(r, 0) >= q for r, q in self._amounts.items())

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        merged = dict(self._amounts)
        for r, q in other._amounts.items():
            merged[r] = merged.get(r, 0) + q
        return ResourceSet(_fixed=merged)

    def __sub__(self, other: "ResourceSet") -> "ResourceSet":
        merged = dict(self._amounts)
        for r, q in other._amounts.items():
            merged[r] = merged.get(r, 0) - q
        if any(q < 0 for q in merged.values()):
            raise ValueError(
                f"resource underflow: {self.to_dict()} - {other.to_dict()}")
        return ResourceSet(_fixed=merged)

    def subtract_clamped(self, other: "ResourceSet") -> "ResourceSet":
        merged = dict(self._amounts)
        for r, q in other._amounts.items():
            merged[r] = max(0, merged.get(r, 0) - q)
        return ResourceSet(_fixed=merged)

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._amounts == other._amounts

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __reduce__(self):
        return (ResourceSet, (self.to_dict(),))


class NodeResources:
    """Total + available resources of one node, plus string labels.

    Labels (reference: label_selector.h, node labels `ray.io/...`) are exact-
    match key/values used by label-selector scheduling.
    """

    def __init__(self, total: ResourceSet, labels: Optional[Dict[str, str]] = None):
        self.total = total
        self.available = total
        self.labels = dict(labels or {})

    def try_allocate(self, demand: ResourceSet) -> bool:
        if not demand.fits(self.available):
            return False
        self.available = self.available - demand
        return True

    def release(self, demand: ResourceSet):
        self.available = self.available + demand
        # Clamp against double-release drift.
        for r, q in list(self.available._amounts.items()):
            cap = self.total._amounts.get(r, 0)
            if q > cap:
                self.available._amounts[r] = cap

    def utilization(self) -> float:
        """Max over resources of used/total — drives hybrid scheduling."""
        best = 0.0
        for r, total in self.total._amounts.items():
            if total <= 0:
                continue
            used = total - self.available._amounts.get(r, 0)
            best = max(best, used / total)
        return best

    def matches_labels(self, selector: Mapping[str, str]) -> bool:
        return all(self.labels.get(k) == v for k, v in selector.items())
