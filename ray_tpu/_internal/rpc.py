"""RPC layer: native epoll transport with an asyncio fallback.

Role-equivalent of the reference's gRPC layer (src/ray/rpc/: GrpcServer,
GrpcClient, RetryableGrpcClient, rpc_chaos.h). Design differences, chosen for
the target environment rather than translated:

- Hot path is native: when src/fastrpc.cpp builds, all socket I/O, framing,
  and write batching run on a C++ epoll thread (the analog of gRPC's
  completion-queue threads); Python sees one loop wakeup per *batch* of
  messages. Without a toolchain the same wire format runs over asyncio
  streams with per-tick write coalescing.
- In-process fast path: servers register in a process-local table; calls to a
  local address dispatch directly on the loop with zero serialization. This is
  what makes "head node in the driver process" mode cheap.
- Retry with exponential backoff for idempotent control-plane calls
  (reference: retryable_grpc_client.cc).
- Fault injection: the seeded chaos registry (`chaos.py`) drops, delays
  and duplicates requests/responses by method pattern (reference:
  rpc_chaos.h, grown into `testing_rpc_failure` + `chaos_spec` rules)
  for deterministic chaos tests.

Wire frames (both transports):
  u32le body_len | u64le msg_id | u8 flags | u16le method_len |
  method utf8 | payload (pickled kwargs / result)
  flags: bit0 = response, bit1 = ok (responses only),
         bit2 = raw (payload is an opaque byte frame dispatched to a
         raw handler with NO kwargs pickling — the flat task path's
         template-announce + delta frames ride this type),
         bit3 = meta (non-raw requests only): u16le meta_len | meta
         bytes follow the method, before the payload — currently the
         "trace_id:span_id" control-plane trace context. OPTIONAL on
         the wire: receivers accept both forms, and the
         RTPU_NO_RPC_METRICS=1 kill switch never sets it, so frames
         are exact-legacy and mixed on/off processes interoperate.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import struct
import threading
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from .config import CONFIG
from .errors import RpcError
from . import aio
from . import rpc_metrics as rpcm
from . import serialization

logger = logging.getLogger(__name__)

Address = Tuple[str, int]
Handler = Callable[..., Awaitable[Any]]

# Frame header: u32 body_len, u64 msg_id, u8 flags, u16 method_len.
_FRAME_HDR = struct.Struct("<IQBH")
_BODY_HDR = struct.Struct("<QBH")
_BODY_HDR_LEN = _BODY_HDR.size
FLAG_RESP = 1
FLAG_OK = 2
FLAG_RAW = 4
FLAG_META = 8
# Internal-only (never on the wire): the payload reaching
# _handle_request is a record the native ring already decoded
# (src/fastrpc.cpp), so dispatch selects the decoded handler table.
FLAG_DECODED = 256

# Decoded event kind -> the method the C classifier matched. The decode
# set is fixed in src/fastrpc.cpp; this table is its Python twin.
_DECODED_KIND_METHOD = {
    3: "push_task",          # KIND_DECODED_PUSH (request: msg_id in rec)
    4: "push_actor_tasks",   # KIND_DECODED_ACTOR_BATCH (oneway)
    5: "actor_tasks_done",   # KIND_DONE_STREAM (oneway)
}
_U64LE = struct.Struct("<Q")
_U16LE = struct.Struct("<H")


def pack_frame(msg_id: int, flags: int, method: bytes,
               payload: bytes, meta: bytes = b"") -> bytes:
    if meta:
        flags |= FLAG_META
        payload = _U16LE.pack(len(meta)) + meta + payload
    return _FRAME_HDR.pack(_BODY_HDR_LEN + len(method) + len(payload),
                           msg_id, flags, len(method)) + method + payload


def unpack_body(body) -> Tuple[int, int, str, bytes, bytes]:
    """Parse a frame body (past the length prefix) -> (id, flags, method,
    payload, meta). Copies the payload: callers may outlive the recv
    buffer. FLAG_META is consumed here (meta extracted, flag stripped),
    so downstream flag logic is identical for both wire forms."""
    msg_id, flags, mlen = _BODY_HDR.unpack_from(body, 0)
    method = bytes(body[_BODY_HDR_LEN:_BODY_HDR_LEN + mlen]).decode() \
        if mlen else ""
    off = _BODY_HDR_LEN + mlen
    meta = b""
    if flags & FLAG_META:
        (meta_len,) = _U16LE.unpack_from(body, off)
        off += 2
        meta = bytes(body[off:off + meta_len])
        off += meta_len
        flags &= ~FLAG_META
    payload = bytes(body[off:])
    return msg_id, flags, method, payload, meta


class FrameReader:
    """Incremental length-prefix frame splitter for the asyncio path."""

    __slots__ = ("_buf", "_off")

    def __init__(self):
        self._buf = bytearray()
        self._off = 0

    def feed(self, chunk: bytes):
        self._buf += chunk
        buf, off = self._buf, self._off
        out = []
        n = len(buf)
        while n - off >= 4:
            (body_len,) = struct.unpack_from("<I", buf, off)
            if n - off - 4 < body_len:
                break
            out.append(memoryview(buf)[off + 4:off + 4 + body_len])
            off += 4 + body_len
        if off == n:
            # Fully consumed: swap in a fresh buffer. The returned
            # memoryviews keep the old bytearray alive and it is never
            # mutated again, so no copy is needed.
            self._buf = bytearray()
            self._off = 0
        else:
            out = [bytes(b) for b in out]
            if off > (1 << 20):
                del self._buf[:off]
                self._off = 0
            else:
                self._off = off
        return out


# --------------------------------------------------------------------------
# Event loop threads: the process-main singleton plus per-owner-shard
# loops (same machinery, explicit lifetime)
# --------------------------------------------------------------------------

class IoLoopThread:
    """One asyncio loop on its own daemon thread with batched cross-
    thread posting. The process-main io loop (`EventLoopThread`) and the
    owner-shard loops are both instances; shard loops are joinable so
    CoreWorker.shutdown / the threads registry can stop them."""

    def __init__(self, name: str = "rtpu-io", joinable: bool = False):
        self.loop = asyncio.new_event_loop()
        # Eager tasks (3.12): a coroutine spawned via ensure_future runs
        # inline to its first true suspension — RPC handlers and actor
        # dispatch that complete synchronously never pay a Task schedule
        # round-trip (~25us/call on the n:n flood path).
        if hasattr(asyncio, "eager_task_factory") and \
                not CONFIG.no_eager_tasks:
            self.loop.set_task_factory(asyncio.eager_task_factory)
        # Stall sanitizer: no-op unless RTPU_SANITIZE armed it at
        # process start (lazy import — lint is tooling, not data plane).
        from .lint import loopstall
        loopstall.register_loop(self.loop, name=name)
        self._post_q: collections.deque = collections.deque()
        self._post_lock = threading.Lock()
        self._post_scheduled = False
        self._stopping = False
        self.thread = threading.Thread(
            target=self._run, name=name, daemon=True)
        # Joinable loops (owner shards) register a stop hook so node
        # teardown can signal and join them; the process-lifetime
        # singleton is tracked for introspection only, never joined
        # (api.shutdown() still needs it after Node.stop()).
        from .threads import register_daemon_thread
        register_daemon_thread(self.thread,
                               stop=self.stop if joinable else None,
                               joinable=joinable)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run_sync(self, coro, timeout: Optional[float] = None):
        if threading.current_thread() is self.thread:
            raise RuntimeError("run_sync called from the io thread (deadlock)")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, coro) -> "asyncio.Future":
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def post(self, coro) -> None:
        """Fire-and-forget a coroutine on the loop with batched wakeups.

        A burst of N posts from caller threads costs ONE loop wakeup
        (call_soon_threadsafe writes a self-pipe byte per call — the
        dominant per-op cost of run_coroutine_threadsafe at high rates).
        Posts from one thread retain their order.
        """
        on_loop = threading.current_thread() is self.thread
        with self._post_lock:
            self._post_q.append(coro)
            if self._post_scheduled:
                return
            self._post_scheduled = True
        if on_loop:
            self.loop.call_soon(self._drain_posts)
        else:
            self.loop.call_soon_threadsafe(self._drain_posts)

    def _drain_posts(self):
        with self._post_lock:
            items = list(self._post_q)
            self._post_q.clear()
            self._post_scheduled = False
        for item in items:
            if callable(item):
                try:
                    item()
                except Exception:
                    logger.exception("posted callback failed")
            else:
                # Posted coroutines are fire-and-forget by contract:
                # route through the logged sink so a failing one is
                # visible (A001).
                aio.spawn(item, loop=self.loop)

    def post_call(self, fn) -> None:
        """Like post() but for a plain callable run on the loop."""
        self.post(fn)

    def pending_posts(self) -> int:
        """Cross-thread posts not yet drained (shard queue-depth probe)."""
        return len(self._post_q)

    def stop(self) -> None:
        """Signal the loop to exit run_forever (idempotent; the threads
        registry joins the thread afterwards). Pending tasks (idle-lease
        cleaners, probe/straggler sweepers) are cancelled first so they
        unwind instead of being destroyed mid-await."""
        if self._stopping:
            return
        self._stopping = True

        def _shutdown():
            for task in asyncio.all_tasks(self.loop):
                task.cancel()
            # Appended after the cancelled tasks' wakeups: they unwind
            # their CancelledError before the loop exits run_forever.
            self.loop.call_soon(self.loop.stop)
        try:
            self.loop.call_soon_threadsafe(_shutdown)
        except RuntimeError:
            logger.debug("loop already closed at stop()", exc_info=True)

    def join(self, timeout: float = 2.0) -> None:
        self.stop()
        self.thread.join(timeout)
        if not self.thread.is_alive():
            try:
                self.loop.close()
            except Exception:
                logger.debug("loop close after join failed", exc_info=True)


class EventLoopThread(IoLoopThread):
    """The process-main io loop singleton."""

    _instance: Optional["EventLoopThread"] = None
    _lock = threading.Lock()

    def __init__(self):
        super().__init__(name="rtpu-io", joinable=False)

    @classmethod
    def get(cls) -> "EventLoopThread":
        with cls._lock:
            if cls._instance is None or not cls._instance.thread.is_alive():
                cls._instance = cls()
            return cls._instance


def get_loop() -> asyncio.AbstractEventLoop:
    return EventLoopThread.get().loop


def _resolve_future(fut: "asyncio.Future", result, exc: Exception = None):
    """Resolve `fut` safely even when it belongs to a DIFFERENT event
    loop than the one delivering the event (a process with two live
    loops: the eventfd reader drains on one, a caller awaited on the
    other). Plain set_result from a foreign thread appends to the other
    loop's ready queue without waking its selector — the caller hangs
    until an unrelated wakeup."""
    try:
        owner = fut.get_loop()
    except Exception:
        owner = None
    try:
        running = asyncio.get_running_loop()
    except RuntimeError:
        running = None
    if owner is not None and owner is not running:
        def _set():
            if fut.done():
                return
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)
        try:
            owner.call_soon_threadsafe(_set)
        except RuntimeError:
            pass  # owner loop closed: caller is gone
        return
    if exc is not None:
        fut.set_exception(exc)
    else:
        fut.set_result(result)


# --------------------------------------------------------------------------
# Chaos / fault injection — the seeded registry in chaos.py owns the
# rules (legacy `testing_rpc_failure` drop specs + the extended
# drop/delay/dup grammar); this layer only consults it at the transport
# decision points.
# --------------------------------------------------------------------------

from .chaos import REGISTRY as CHAOS  # noqa: E402  (after config import)

# Sentinel distinguishing "use the configured default timeout" from
# timeout=None, which means no deadline at all (unbounded pushes).
DEFAULT_TIMEOUT = object()

# Lazy tracing accessor: ray_tpu.util's package __init__ pulls in the
# core (placement groups -> core_worker), which imports this module —
# a module-scope import would cycle. After the first call this is a
# plain global read.
_tracing_mod = None


def _tracing():
    global _tracing_mod
    if _tracing_mod is None:
        from ..util import tracing
        _tracing_mod = tracing
    return _tracing_mod


# --------------------------------------------------------------------------
# Write coalescing
# --------------------------------------------------------------------------

# Above this much buffered outbound data, writers await drain() so a slow
# peer applies backpressure instead of unbounded memory growth.
_DRAIN_THRESHOLD = 8 << 20


class CoalescingWriter:
    """Batches frames produced within one event-loop tick into one
    transport write (one syscall), instead of a send() per frame.

    All methods must run on the event loop. Small frames dominate the
    control plane; a burst of replies/calls in one tick becomes a single
    b"".join + write. Large frames are written directly (no join copy).
    """

    __slots__ = ("_writer", "_buf", "_buf_bytes", "_scheduled")

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer
        self._buf: list = []
        self._buf_bytes = 0
        self._scheduled = False

    def write(self, data: bytes):
        if len(data) >= (1 << 16):
            self._flush()
            self._writer.write(data)
            return
        self._buf.append(data)
        self._buf_bytes += len(data)
        if not self._scheduled:
            self._scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    def _flush(self):
        self._scheduled = False
        if not self._buf:
            return
        buf, self._buf = self._buf, []
        self._buf_bytes = 0
        try:
            if len(buf) == 1:
                self._writer.write(buf[0])
            else:
                self._writer.write(b"".join(buf))
        except (ConnectionResetError, RuntimeError):
            pass

    def needs_drain(self) -> bool:
        transport = self._writer.transport
        size = transport.get_write_buffer_size() if transport else 0
        return size + self._buf_bytes > _DRAIN_THRESHOLD

    async def drain(self):
        self._flush()
        try:
            await self._writer.drain()
        except (ConnectionResetError, RuntimeError):
            pass


# --------------------------------------------------------------------------
# Native I/O core plumbing
# --------------------------------------------------------------------------

_native_checked = False
_native_instance = None


def _native_io():
    """The process NativeIO singleton, or None (build failure / disabled)."""
    global _native_checked, _native_instance
    if not _native_checked:
        try:
            from .._native.fastrpc import NativeIO
            _native_instance = NativeIO.get()
        except Exception:
            logger.exception("native rpc unavailable; using asyncio")
            _native_instance = None
        _native_checked = True
    return _native_instance


async def _native_drain_wait(nio, conn_id: int):
    """Poll-based backpressure: wait until the native out-queue drains."""
    while nio.out_bytes(conn_id) > _DRAIN_THRESHOLD // 2:
        await asyncio.sleep(0.005)


class NativeCoalescer:
    """Per-connection frame batcher for the native transport: frames
    produced within one loop tick become one ctypes send (one buffer copy,
    one io-thread wakeup). Mirrors CoalescingWriter for asyncio."""

    __slots__ = ("_nio", "_conn", "_buf", "_scheduled")

    def __init__(self, nio, conn_id: int):
        self._nio = nio
        self._conn = conn_id
        self._buf: list = []
        self._scheduled = False

    def write(self, frame: bytes) -> bool:
        if len(frame) >= (1 << 16):
            self._flush()
            return self._nio.send(self._conn, frame)
        self._buf.append(frame)
        if not self._scheduled:
            self._scheduled = True
            try:
                asyncio.get_running_loop().call_soon(self._flush)
            except RuntimeError:
                # loop already stopped (shard teardown racing a late
                # reply): send inline instead of dropping the frame
                self._flush()
        return True

    def _flush(self):
        self._scheduled = False
        if not self._buf:
            return
        buf, self._buf = self._buf, []
        self._nio.send(self._conn,
                       buf[0] if len(buf) == 1 else b"".join(buf))


# --------------------------------------------------------------------------
# Server
# --------------------------------------------------------------------------

_local_servers: Dict[Address, "RpcServer"] = {}
_local_servers_lock = threading.Lock()


def _local_owner_loop(server: "RpcServer"):
    """The loop an in-process dispatch must run on, or None when the
    caller's running loop already owns the server (the common case: one
    loop per process, zero-hop dispatch)."""
    owner = server.loop
    if owner is None:
        return None
    try:
        running = asyncio.get_running_loop()
    except RuntimeError:
        running = None
    return None if owner is running else owner


def _log_oneway_failure(cfut, method: str) -> None:
    """Cross-loop oneway dispatch returns a concurrent Future nobody
    awaits; without this hook a failing handler's exception would be
    GC'd unobserved (the same-loop ensure_future path at least gets the
    loop's 'Task exception was never retrieved' log)."""
    def _done(f):
        exc = f.exception()
        if exc is not None:
            logger.warning("oneway %s handler failed on owner loop: %r",
                           method, exc)
    cfut.add_done_callback(_done)


async def _await_on_owner_loop(owner_loop, coro,
                               timeout: Optional[float]):
    """In-process call crossing loops (an owner shard calling the main-
    loop raylet/GCS): run the handler on its owner loop, await the
    result from the caller's loop. This is the shard<->main mailbox for
    local dispatch — without it, the zero-serialization fast path would
    execute single-loop server state on the wrong thread."""
    cfut = asyncio.run_coroutine_threadsafe(coro, owner_loop)
    return await asyncio.wait_for(asyncio.wrap_future(cfut), timeout)


class RpcServer:
    def __init__(self, name: str, nio=None):
        self.name = name
        self._handlers: Dict[str, Handler] = {}
        self._raw_handlers: Dict[str, Handler] = {}
        self._decoded_handlers: Dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.address: Optional[Address] = None
        # Owner loop, recorded at start(): handlers and connection state
        # live here. The in-process fast path hops to this loop when the
        # caller runs on a different one (owner shards) — dispatching a
        # handler on a foreign loop would interleave two loops through
        # state that is single-loop by design.
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        # Explicit ring override (owner shards); None = the process ring.
        self._nio_pref = nio
        self._native = None            # NativeIO when serving natively
        self._native_listener: Optional[int] = None
        self._native_conns: set = set()
        # 1/64 sampling tick for the handler-latency histogram
        # (single-loop server: no race on the increment).
        self._obs_tick = 0

    def register(self, method: str, handler: Handler):
        self._handlers[method] = handler

    def register_raw(self, method: str, handler: Handler):
        """Handler for FLAG_RAW frames: called with the payload bytes
        as-is — no kwargs pickling on either side of the wire."""
        self._raw_handlers[method] = handler

    def register_decoded(self, method: str, handler: Handler):
        """Handler for frames the native ring pre-decoded (kind 3-5
        events): called with the C decoder's record bytes instead of the
        raw wire payload. Requests routed here still flow through
        _handle_request, so chaos injection and the reply path are
        identical to the raw route."""
        self._decoded_handlers[method] = handler

    def register_instance(self, obj: Any, prefix: str = ""):
        """Register every `async def handle_<x>` method of obj as rpc `<x>`."""
        for attr in dir(obj):
            if attr.startswith("handle_"):
                self.register(prefix + attr[len("handle_"):], getattr(obj, attr))

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Address:
        self.loop = asyncio.get_running_loop()
        # nio=False forces the asyncio transport: a shard whose ring
        # allocation failed must NOT fall through to the process ring —
        # ring 0 drains on the MAIN loop, which would run this server's
        # handlers off its owner loop.
        nio = self._nio_pref if self._nio_pref is not None else _native_io()
        if nio is False:
            nio = None
        if nio is not None:
            nio.attach(asyncio.get_running_loop())
            res = nio.listen(host, port, self._native_accept)
            if res is not None:
                self._native = nio
                self._native_listener, bound_port = res
                self.address = (host, bound_port)
                with _local_servers_lock:
                    _local_servers[self.address] = self
                return self.address
            logger.warning("native listen failed; falling back to asyncio")
        self._server = await asyncio.start_server(self._on_conn, host, port)
        sock = self._server.sockets[0]
        self.address = sock.getsockname()[:2]
        with _local_servers_lock:
            _local_servers[self.address] = self
        return self.address

    async def stop(self):
        if self._native is not None:
            self._native.close(self._native_listener,
                               listener_id=self._native_listener)
            for conn in list(self._native_conns):
                self._native.close(conn)
            self._native_conns.clear()
        if self._server is not None:
            self._server.close()
            try:
                # 3.12's wait_closed blocks until every open connection
                # finishes; peers hold persistent connections, so cap it —
                # the listening socket is already closed by close().
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except Exception:
                logger.debug("server wait_closed timed out; peers hold "
                             "persistent connections", exc_info=True)
        with _local_servers_lock:
            _local_servers.pop(self.address, None)

    async def _dispatch(self, method: str, payload: Dict[str, Any]) -> Any:
        handler = self._handlers.get(method)
        if handler is None:
            raise RpcError(f"{self.name}: no handler for method {method!r}")
        return await handler(**payload)

    # -- native transport ------------------------------------------------

    def _native_accept(self, conn_id: int):
        self._native_conns.add(conn_id)
        coalescer = NativeCoalescer(self._native, conn_id)

        def sink(kind, body):
            if kind == 2:  # closed
                self._native_conns.discard(conn_id)
                return
            if kind >= 3:
                # Pre-decoded by the C ring: the body IS the decoded
                # record. One copy out of the reused drain buffer, then
                # the normal dispatch (chaos, reply, backpressure)
                # against the decoded handler table. kind-3 requests
                # carry their msg_id as the record's first field; 4/5
                # are oneway streams.
                method = _DECODED_KIND_METHOD.get(kind)
                if method is None:
                    logger.warning("unknown decoded event kind %d", kind)
                    return
                msg_id = _U64LE.unpack_from(body, 0)[0] if kind == 3 else 0
                asyncio.ensure_future(
                    self._handle_request(method, bytes(body), msg_id,
                                         self._native_reply, coalescer,
                                         FLAG_RAW | FLAG_DECODED))
                return
            msg_id, flags, method, payload, meta = unpack_body(body)
            asyncio.ensure_future(
                self._handle_request(method, payload, msg_id,
                                     self._native_reply, coalescer, flags,
                                     meta=meta))
        return sink

    def _native_reply(self, coalescer: "NativeCoalescer", frame: bytes):
        coalescer.write(frame)
        if self._native.out_bytes(coalescer._conn) > _DRAIN_THRESHOLD:
            return _native_drain_wait(self._native, coalescer._conn)

    # -- asyncio transport -----------------------------------------------

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter):
        cw = CoalescingWriter(writer)
        frames = FrameReader()

        def reply(_conn, frame):
            cw.write(frame)
            if cw.needs_drain():
                return cw.drain()
        try:
            while True:
                chunk = await reader.read(1 << 20)
                if not chunk:
                    break
                for body in frames.feed(chunk):
                    msg_id, flags, method, payload, meta = unpack_body(body)
                    asyncio.ensure_future(
                        self._handle_request(method, payload, msg_id,
                                             reply, None, flags,
                                             meta=meta))
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                logger.debug("connection close failed", exc_info=True)

    # -- shared dispatch -------------------------------------------------

    async def _handle_request(self, method: str, payload: bytes,
                              msg_id: int, reply, conn, flags: int = 0,
                              meta: bytes = b""):
        if CHAOS.drop_request(method):
            return
        delay = CHAOS.request_delay(method)
        if delay > 0:
            await asyncio.sleep(delay)
        m = rpcm.metrics()
        start = 0.0
        if m is not None:
            rpcm.inflight_delta("server", 1)
            rpcm.note_bytes(method, "in", len(payload))
            if meta:
                # Adopt the caller's trace context for the handler: this
                # coroutine is its own task, so the set is task-local —
                # RPCs the handler issues chain as children of the
                # client-side rpc span shipped in the meta.
                tctx = rpcm.parse_meta(meta)
                if tctx is not None:
                    _tracing().set_trace_context(tctx)
            start = time.perf_counter()
        try:
            if flags & FLAG_RAW:
                if flags & FLAG_DECODED:
                    handler = self._decoded_handlers.get(method)
                else:
                    handler = self._raw_handlers.get(method)
                if handler is None:
                    raise RpcError(
                        f"{self.name}: no raw handler for {method!r}")
                result = await handler(payload)
            else:
                kwargs = serialization.loads(payload) if payload else {}
                result = await self._dispatch(method, kwargs)
            ok, body = True, result
        except BaseException as e:  # noqa: BLE001 — errors cross the wire
            ok, body = False, e
            if msg_id == 0:
                logger.warning("one-way rpc %s failed: %s", method, e)
        if m is not None:
            dur = time.perf_counter() - start
            rpcm.inflight_delta("server", -1)
            self._obs_tick = (self._obs_tick + 1) & 63
            if self._obs_tick == 0 \
                    or dur >= float(CONFIG.rpc_slow_call_s):
                m.server_seconds.observe(dur, tags={"method": method})
        if msg_id == 0:
            return  # one-way message: no response frame
        if CHAOS.drop_response(method):
            return
        try:
            data = serialization.dumps(body)
        except Exception as e:
            ok, data = False, serialization.dumps(
                RpcError(f"unpicklable reply: {e}"))
        flags = FLAG_RESP | (FLAG_OK if ok else 0)
        frame = pack_frame(msg_id, flags, b"", data)
        if m is not None:
            rpcm.note_bytes(method, "out", len(frame))
        waiter = reply(conn, frame)
        if waiter is not None:
            await waiter  # transport backpressure
        if CHAOS.duplicate_response(method):
            # Chaos dup: deliver the reply twice — the client's pending-
            # future pop makes the second frame a no-op there, but
            # callers above (lease grants, death reports) must stay
            # idempotent against transport-level redelivery.
            waiter = reply(conn, frame)
            if waiter is not None:
                await waiter


# --------------------------------------------------------------------------
# Client
# --------------------------------------------------------------------------

class RpcClient:
    """Client to one remote server; persistent connection, multiplexed ids.

    Loop-affine: the connection, pending-reply futures, and (optionally)
    the native ring all live on the loop that first uses the client —
    owner shards therefore keep their OWN ClientPool rather than sharing
    the process pool across loops."""

    def __init__(self, address: Address, nio=None):
        self.address = (address[0], int(address[1]))
        self._nio_pref = nio          # explicit ring (owner shards)
        self._writer: Optional[asyncio.StreamWriter] = None
        self._cw: Optional[CoalescingWriter] = None
        self._native = None           # NativeIO when connected natively
        self._native_conn: Optional[int] = None
        self._native_cw: Optional["NativeCoalescer"] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._conn_lock: Optional[asyncio.Lock] = None
        self._reader_task: Optional[asyncio.Task] = None
        # 1/64 sampling tick for the client-latency histogram
        # (loop-affine client: no race on the increment).
        self._obs_tick = 0

    def _local(self) -> Optional[RpcServer]:
        with _local_servers_lock:
            return _local_servers.get(self.address)

    def _connected(self) -> bool:
        if self._native_conn is not None:
            return True
        return self._writer is not None and not self._writer.is_closing()

    async def _ensure_conn(self):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._connected():
                return
            nio = self._nio_pref if self._nio_pref is not None \
                else _native_io()
            if nio is False:
                nio = None  # forced asyncio transport (see RpcServer.start)
            if nio is not None:
                loop = asyncio.get_running_loop()
                nio.attach(loop)
                host, port = self.address
                timeout_ms = int(CONFIG.rpc_connect_timeout_s * 1000)
                conn = await loop.run_in_executor(
                    None, nio.connect, host, port, timeout_ms)
                if conn is None:
                    raise ConnectionError(
                        f"connect to {self.address} failed")
                self._native = nio
                self._native_conn = conn
                self._native_cw = NativeCoalescer(nio, conn)
                # On the loop: safe w.r.t. _drain's orphan buffering. A
                # close that raced the connect flushes here and fails the
                # (not yet issued) calls via _fail_pending.
                nio.register(conn, self._on_native_event)
                return
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(*self.address),
                CONFIG.rpc_connect_timeout_s)
            self._writer = writer
            self._cw = CoalescingWriter(writer)
            self._reader_task = asyncio.ensure_future(self._read_loop(reader))

    def _on_native_event(self, kind: int, body):
        if kind == 2:  # closed
            self._fail_pending(
                RpcError(f"connection to {self.address} closed"))
            return
        msg_id, flags, _method, payload, _meta = unpack_body(body)
        fut = self._pending.pop(msg_id, None)
        if fut is not None and not fut.done():
            _resolve_future(fut, (flags, payload))

    async def _read_loop(self, reader: asyncio.StreamReader):
        frames = FrameReader()
        try:
            while True:
                chunk = await reader.read(1 << 20)
                if not chunk:
                    break
                for body in frames.feed(chunk):
                    msg_id, flags, _method, payload, _meta = unpack_body(body)
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        _resolve_future(fut, (flags, payload))
        except Exception as e:
            self._fail_pending(RpcError(f"connection to {self.address} lost: {e}"))
            return
        self._fail_pending(RpcError(f"connection to {self.address} closed"))

    async def _send_frame(self, frame: bytes):
        """Shared transport write (native or asyncio) with drain-based
        backpressure — the only difference between call/oneway and their
        _raw variants is how the frame is built."""
        if self._native_conn is not None:
            conn = self._native_conn
            if not self._native_cw.write(frame):
                raise ConnectionError(f"send to {self.address} failed")
            if self._native.out_bytes(conn) > _DRAIN_THRESHOLD:
                await _native_drain_wait(self._native, conn)
        else:
            cw = self._cw
            cw.write(frame)
            if cw.needs_drain():
                await cw.drain()

    def _fail_pending(self, err: Exception):
        self._writer = None
        self._cw = None
        if self._native_conn is not None and self._native is not None:
            self._native.close(self._native_conn)
        self._native_conn = None
        self._native_cw = None
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                _resolve_future(fut, None, exc=err)

    async def call(self, method: str, timeout: Optional[float] = DEFAULT_TIMEOUT,
                   retries: int = 0, **kwargs) -> Any:
        """Call `method`. Retries only on transport errors (idempotent use).

        timeout=None disables the deadline entirely (used for pushes whose
        execution time is unbounded, e.g. a long-running actor task); the
        connection read-loop still fails the call if the peer dies."""
        if timeout is DEFAULT_TIMEOUT:
            timeout = CONFIG.rpc_call_timeout_s
        if not rpcm.enabled():
            return await self._call_retrying(method, kwargs, timeout,
                                             retries)
        rpcm.inflight_delta("client", 1)
        start = time.perf_counter()
        try:
            return await self._call_retrying(method, kwargs, timeout,
                                             retries)
        finally:
            rpcm.inflight_delta("client", -1)
            self._observe_call(method, time.perf_counter() - start)

    async def _call_retrying(self, method: str, kwargs: Dict[str, Any],
                             timeout: Optional[float], retries: int) -> Any:
        attempt = 0
        bo = None  # built on first failure — the success path pays nothing
        while True:
            try:
                return await self._call_once(method, kwargs, timeout)
            except (RpcError, ConnectionError, asyncio.TimeoutError, OSError) as e:
                m = rpcm.metrics()
                if m is not None:
                    m.transport_errors.inc(tags={"method": method})
                attempt += 1
                if attempt > retries:
                    if isinstance(e, asyncio.TimeoutError):
                        raise RpcError(
                            f"rpc {method} to {self.address} timed out") from e
                    raise
                if bo is None:
                    from .backoff import Backoff
                    bo = Backoff(
                        base_s=CONFIG.rpc_retry_base_delay_ms / 1000.0,
                        max_s=CONFIG.rpc_retry_max_delay_ms / 1000.0,
                        site="rpc_call")
                await bo.async_sleep()

    def _observe_call(self, method: str, duration_s: float):
        """Per-logical-call accounting: 1/64-sampled latency histogram
        (slow calls always recorded — they're the ones the p99 and the
        watchdog exist for) + watchdog attribution."""
        m = rpcm.metrics()
        if m is None:
            return
        slow = duration_s >= float(CONFIG.rpc_slow_call_s)
        self._obs_tick = (self._obs_tick + 1) & 63
        if self._obs_tick == 0 or slow:
            m.client_seconds.observe(duration_s, tags={"method": method})
        if slow:
            wd = rpcm.watchdog()
            if wd is not None:
                wd.note(method,
                        f"{self.address[0]}:{self.address[1]}",
                        duration_s)

    async def _call_once(self, method: str, payload: Dict[str, Any],
                         timeout: float) -> Any:
        local = self._local()
        if local is not None:
            # In-process fast path — no sockets, no serialization. A
            # caller on a foreign loop (owner shard -> main-loop raylet/
            # GCS) hops to the server's owner loop instead of running
            # its handler here.
            if CHAOS.drop_request(method) or CHAOS.drop_response(method):
                raise asyncio.TimeoutError()
            delay = CHAOS.request_delay(method)
            if delay > 0:
                await asyncio.sleep(delay)
            owner = _local_owner_loop(local)
            if owner is not None:
                return await _await_on_owner_loop(
                    owner, local._dispatch(method, payload), timeout)
            return await asyncio.wait_for(
                local._dispatch(method, payload), timeout)
        return await self._call_frame(
            0, method, serialization.dumps(payload) if payload else b"",
            timeout)

    async def _call_frame(self, flags: int, method: str, payload: bytes,
                          timeout: Optional[float]) -> Any:
        """Shared request/response tail: pending-future bookkeeping, one
        transport write, reply decode (pickled either way)."""
        await self._ensure_conn()
        self._next_id += 1
        msg_id = self._next_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        meta = b""
        span = None
        m = rpcm.metrics()
        if m is not None and not (flags & FLAG_RAW) \
                and method not in rpcm.NO_SPAN_METHODS:
            ctx = _tracing().get_trace_context()
            if ctx is not None:
                # Pre-generate the rpc span's id so the wire meta can
                # ship it: the server adopts (trace_id, rpc_span_id),
                # making handler-issued RPCs children of this hop in
                # the trace tree.
                span_id = _tracing().new_span_id()
                meta = f"{ctx[0]}:{span_id}".encode()
                span = (ctx, span_id, time.time())
        frame = pack_frame(msg_id, flags, method.encode(), payload, meta)
        if m is not None:
            rpcm.note_bytes(method, "out", len(frame))
        try:
            await self._send_frame(frame)
            rflags, data = await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(msg_id, None)
            if span is not None:
                ctx, span_id, span_start = span
                _tracing().record_child_span(
                    f"rpc:{method}", ctx, span_start, time.time(),
                    span_id=span_id)
        if m is not None:
            rpcm.note_bytes(method, "in", len(data))
        body = serialization.loads(data)
        if not (rflags & FLAG_OK):
            raise body
        return body

    async def oneway(self, method: str, **kwargs):
        """Send a message expecting no response (msg id 0). Loses silently
        on transport failure mid-flight; callers rely on higher-level
        liveness (GCS health/pubsub) for recovery. Raises only if no
        connection can be established."""
        local = self._local()
        if local is not None:
            if not CHAOS.drop_request(method):
                owner = _local_owner_loop(local)
                if owner is not None:
                    _log_oneway_failure(
                        asyncio.run_coroutine_threadsafe(
                            local._dispatch(method, kwargs), owner),
                        method)
                else:
                    aio.spawn(local._dispatch(method, kwargs),
                              what=f"oneway:{method}")
            return
        await self._ensure_conn()
        frame = pack_frame(0, 0, method.encode(),
                           serialization.dumps(kwargs) if kwargs else b"")
        rpcm.note_bytes(method, "out", len(frame))
        await self._send_frame(frame)

    async def call_raw(self, method: str, payload: bytes,
                       timeout: Optional[float] = DEFAULT_TIMEOUT) -> Any:
        """Request/response over a FLAG_RAW frame: the request payload
        crosses as-is into the server's raw handler (no kwargs pickling);
        the reply travels the normal pickled-response path."""
        if timeout is DEFAULT_TIMEOUT:
            timeout = CONFIG.rpc_call_timeout_s
        if not rpcm.enabled():
            return await self._call_raw_once(method, payload, timeout)
        rpcm.inflight_delta("client", 1)
        start = time.perf_counter()
        try:
            return await self._call_raw_once(method, payload, timeout)
        finally:
            rpcm.inflight_delta("client", -1)
            self._observe_call(method, time.perf_counter() - start)

    async def _call_raw_once(self, method: str, payload: bytes,
                             timeout: Optional[float]) -> Any:
        local = self._local()
        if local is not None:
            if CHAOS.drop_request(method) or CHAOS.drop_response(method):
                raise asyncio.TimeoutError()
            handler = local._raw_handlers.get(method)
            if handler is None:
                raise RpcError(f"no raw handler for {method!r}")
            owner = _local_owner_loop(local)
            if owner is not None:
                return await _await_on_owner_loop(
                    owner, handler(payload), timeout)
            return await asyncio.wait_for(handler(payload), timeout)
        return await self._call_frame(FLAG_RAW, method, payload, timeout)

    async def oneway_raw(self, method: str, payload: bytes):
        """One-way FLAG_RAW frame: `payload` crosses the wire as-is and
        lands in the server's raw handler — no pickler on either side
        (the flat task path's template+delta frames)."""
        local = self._local()
        if local is not None:
            if not CHAOS.drop_request(method):
                handler = local._raw_handlers.get(method)
                if handler is None:
                    raise RpcError(f"no raw handler for {method!r}")
                owner = _local_owner_loop(local)
                if owner is not None:
                    _log_oneway_failure(
                        asyncio.run_coroutine_threadsafe(handler(payload),
                                                         owner),
                        method)
                else:
                    aio.spawn(handler(payload),
                              what=f"oneway_raw:{method}")
            return
        await self._ensure_conn()
        frame = pack_frame(0, FLAG_RAW, method.encode(), payload)
        rpcm.note_bytes(method, "out", len(frame))
        await self._send_frame(frame)

    def call_sync(self, method: str, timeout: Optional[float] = DEFAULT_TIMEOUT,
                  retries: int = 0, **kwargs) -> Any:
        if timeout is DEFAULT_TIMEOUT:
            timeout = CONFIG.rpc_call_timeout_s
        total = (timeout * (retries + 1) + 10) if timeout is not None else None
        return EventLoopThread.get().run_sync(
            self.call(method, timeout=timeout, retries=retries, **kwargs),
            timeout=total)

    async def close(self):
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                logger.debug("client writer close failed", exc_info=True)
        self._writer = None
        if self._native_conn is not None and self._native is not None:
            self._native.close(self._native_conn)
            self._native_conn = None


class ClientPool:
    """Cache of RpcClients keyed by address (reference: per-service
    pools). One pool per loop: owner shards construct their own with
    their ring so every cached client stays loop-affine."""

    def __init__(self, nio=None, loop_thread: Optional[IoLoopThread] = None):
        self._clients: Dict[Address, RpcClient] = {}
        self._lock = threading.Lock()
        self._nio = nio
        self._loop_thread = loop_thread

    def get(self, address: Address) -> RpcClient:
        address = (address[0], int(address[1]))
        with self._lock:
            client = self._clients.get(address)
            if client is None:
                client = RpcClient(address, nio=self._nio)
                self._clients[address] = client
            return client

    def invalidate(self, address: Address):
        with self._lock:
            client = self._clients.pop(tuple(address), None)
        if client is not None:
            (self._loop_thread or EventLoopThread.get()).call_soon(
                client.close())

    def close_all(self):
        """Close every cached client on the pool's loop (shard teardown)."""
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        lt = self._loop_thread or EventLoopThread.get()
        for client in clients:
            try:
                lt.call_soon(client.close())
            except RuntimeError:
                logger.debug("client close after loop stop skipped",
                             exc_info=True)
