"""Asyncio RPC layer.

Role-equivalent of the reference's gRPC layer (src/ray/rpc/: GrpcServer,
GrpcClient, RetryableGrpcClient, rpc_chaos.h). Design differences, chosen for
the target environment rather than translated:

- Transport is length-prefixed msgpack over TCP with pickled payloads —
  one event-loop thread per process serves every component in that process
  (the reference gives each server its own polling threads).
- In-process fast path: servers register in a process-local table; calls to a
  local address dispatch directly on the loop with zero serialization. This is
  what makes "head node in the driver process" mode cheap.
- Retry with exponential backoff for idempotent control-plane calls
  (reference: retryable_grpc_client.cc).
- Fault injection: `testing_rpc_failure` config drops requests/responses by
  method pattern (reference: rpc_chaos.h) for chaos tests.

Wire frames: 4-byte big-endian length + msgpack map.
  request:  {"i": id, "m": method, "p": pickled-args-bytes}
  response: {"i": id, "ok": bool, "p": pickled-result-or-exception}
"""

from __future__ import annotations

import asyncio
import logging
import random
import struct
import threading
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

import msgpack

from .config import CONFIG
from .errors import RpcError
from . import serialization

logger = logging.getLogger(__name__)

Address = Tuple[str, int]
Handler = Callable[..., Awaitable[Any]]

_HEADER = struct.Struct(">I")


# --------------------------------------------------------------------------
# Event loop singleton (one io thread per process)
# --------------------------------------------------------------------------

class EventLoopThread:
    _instance: Optional["EventLoopThread"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self._run, name="rtpu-io", daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    @classmethod
    def get(cls) -> "EventLoopThread":
        with cls._lock:
            if cls._instance is None or not cls._instance.thread.is_alive():
                cls._instance = cls()
            return cls._instance

    def run_sync(self, coro, timeout: Optional[float] = None):
        if threading.current_thread() is self.thread:
            raise RuntimeError("run_sync called from the io thread (deadlock)")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, coro) -> "asyncio.Future":
        return asyncio.run_coroutine_threadsafe(coro, self.loop)


def get_loop() -> asyncio.AbstractEventLoop:
    return EventLoopThread.get().loop


# --------------------------------------------------------------------------
# Chaos / fault injection
# --------------------------------------------------------------------------

class _Chaos:
    """Parses `testing_rpc_failure` = "method:req_p:resp_p,..." and decides
    whether to drop a request or response. `method` may be a substring."""

    def __init__(self):
        self._rules = None
        self._spec = None

    def _load(self):
        spec = CONFIG.testing_rpc_failure
        if spec == self._spec:
            return
        self._spec = spec
        rules = []
        if spec:
            for entry in spec.split(","):
                parts = entry.split(":")
                rules.append((parts[0], float(parts[1]), float(parts[2])))
        self._rules = rules

    def drop_request(self, method: str) -> bool:
        self._load()
        return any(pat in method and random.random() < p
                   for pat, p, _ in self._rules)

    def drop_response(self, method: str) -> bool:
        self._load()
        return any(pat in method and random.random() < p
                   for pat, _, p in self._rules)


CHAOS = _Chaos()

# Sentinel distinguishing "use the configured default timeout" from
# timeout=None, which means no deadline at all (unbounded pushes).
DEFAULT_TIMEOUT = object()


# --------------------------------------------------------------------------
# Server
# --------------------------------------------------------------------------

_local_servers: Dict[Address, "RpcServer"] = {}
_local_servers_lock = threading.Lock()


class RpcServer:
    def __init__(self, name: str):
        self.name = name
        self._handlers: Dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.address: Optional[Address] = None

    def register(self, method: str, handler: Handler):
        self._handlers[method] = handler

    def register_instance(self, obj: Any, prefix: str = ""):
        """Register every `async def handle_<x>` method of obj as rpc `<x>`."""
        for attr in dir(obj):
            if attr.startswith("handle_"):
                self.register(prefix + attr[len("handle_"):], getattr(obj, attr))

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Address:
        self._server = await asyncio.start_server(self._on_conn, host, port)
        sock = self._server.sockets[0]
        self.address = sock.getsockname()[:2]
        with _local_servers_lock:
            _local_servers[self.address] = self
        return self.address

    async def stop(self):
        if self._server is not None:
            self._server.close()
            try:
                # 3.12's wait_closed blocks until every open connection
                # finishes; peers hold persistent connections, so cap it —
                # the listening socket is already closed by close().
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except Exception:
                pass
        with _local_servers_lock:
            _local_servers.pop(self.address, None)

    async def _dispatch(self, method: str, payload: Dict[str, Any]) -> Any:
        handler = self._handlers.get(method)
        if handler is None:
            raise RpcError(f"{self.name}: no handler for method {method!r}")
        return await handler(**payload)

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter):
        try:
            unpacker = msgpack.Unpacker(raw=False, max_buffer_size=1 << 31)
            while True:
                chunk = await reader.read(1 << 20)
                if not chunk:
                    break
                unpacker.feed(chunk)
                for msg in unpacker:
                    asyncio.ensure_future(self._handle_msg(msg, writer))
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _handle_msg(self, msg: Dict[str, Any],
                          writer: asyncio.StreamWriter):
        method = msg["m"]
        if CHAOS.drop_request(method):
            return
        try:
            payload = serialization.loads(msg["p"]) if msg["p"] else {}
            result = await self._dispatch(method, payload)
            ok, body = True, result
        except BaseException as e:  # noqa: BLE001 — errors cross the wire
            ok, body = False, e
        if CHAOS.drop_response(method):
            return
        try:
            data = serialization.dumps(body)
        except Exception as e:
            ok, data = False, serialization.dumps(RpcError(f"unpicklable reply: {e}"))
        out = msgpack.packb({"i": msg["i"], "ok": ok, "p": data})
        try:
            writer.write(out)
            await writer.drain()
        except (ConnectionResetError, RuntimeError):
            pass


# --------------------------------------------------------------------------
# Client
# --------------------------------------------------------------------------

class RpcClient:
    """Client to one remote server; persistent connection, multiplexed ids."""

    def __init__(self, address: Address):
        self.address = (address[0], int(address[1]))
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._conn_lock: Optional[asyncio.Lock] = None
        self._reader_task: Optional[asyncio.Task] = None

    def _local(self) -> Optional[RpcServer]:
        with _local_servers_lock:
            return _local_servers.get(self.address)

    async def _ensure_conn(self):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(*self.address),
                CONFIG.rpc_connect_timeout_s)
            self._writer = writer
            self._reader_task = asyncio.ensure_future(self._read_loop(reader))

    async def _read_loop(self, reader: asyncio.StreamReader):
        unpacker = msgpack.Unpacker(raw=False, max_buffer_size=1 << 31)
        try:
            while True:
                chunk = await reader.read(1 << 20)
                if not chunk:
                    break
                unpacker.feed(chunk)
                for msg in unpacker:
                    fut = self._pending.pop(msg["i"], None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except Exception as e:
            self._fail_pending(RpcError(f"connection to {self.address} lost: {e}"))
            return
        self._fail_pending(RpcError(f"connection to {self.address} closed"))

    def _fail_pending(self, err: Exception):
        self._writer = None
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(err)

    async def call(self, method: str, timeout: Optional[float] = DEFAULT_TIMEOUT,
                   retries: int = 0, **kwargs) -> Any:
        """Call `method`. Retries only on transport errors (idempotent use).

        timeout=None disables the deadline entirely (used for pushes whose
        execution time is unbounded, e.g. a long-running actor task); the
        connection read-loop still fails the call if the peer dies."""
        if timeout is DEFAULT_TIMEOUT:
            timeout = CONFIG.rpc_call_timeout_s
        attempt = 0
        while True:
            try:
                return await self._call_once(method, kwargs, timeout)
            except (RpcError, ConnectionError, asyncio.TimeoutError, OSError) as e:
                attempt += 1
                if attempt > retries:
                    if isinstance(e, asyncio.TimeoutError):
                        raise RpcError(
                            f"rpc {method} to {self.address} timed out") from e
                    raise
                delay = min(
                    CONFIG.rpc_retry_base_delay_ms * (2 ** (attempt - 1)),
                    CONFIG.rpc_retry_max_delay_ms) / 1000.0
                await asyncio.sleep(delay * (0.5 + random.random()))

    async def _call_once(self, method: str, payload: Dict[str, Any],
                         timeout: float) -> Any:
        local = self._local()
        if local is not None:
            # In-process fast path — no sockets, no serialization.
            if CHAOS.drop_request(method) or CHAOS.drop_response(method):
                raise asyncio.TimeoutError()
            return await asyncio.wait_for(
                local._dispatch(method, payload), timeout)
        await self._ensure_conn()
        self._next_id += 1
        msg_id = self._next_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        data = msgpack.packb({
            "i": msg_id, "m": method, "p": serialization.dumps(payload)})
        self._writer.write(data)
        try:
            await self._writer.drain()
            msg = await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(msg_id, None)
        body = serialization.loads(msg["p"])
        if not msg["ok"]:
            raise body
        return body

    def call_sync(self, method: str, timeout: Optional[float] = DEFAULT_TIMEOUT,
                  retries: int = 0, **kwargs) -> Any:
        if timeout is DEFAULT_TIMEOUT:
            timeout = CONFIG.rpc_call_timeout_s
        total = (timeout * (retries + 1) + 10) if timeout is not None else None
        return EventLoopThread.get().run_sync(
            self.call(method, timeout=timeout, retries=retries, **kwargs),
            timeout=total)

    async def close(self):
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._writer = None


class ClientPool:
    """Cache of RpcClients keyed by address (reference: per-service pools)."""

    def __init__(self):
        self._clients: Dict[Address, RpcClient] = {}
        self._lock = threading.Lock()

    def get(self, address: Address) -> RpcClient:
        address = (address[0], int(address[1]))
        with self._lock:
            client = self._clients.get(address)
            if client is None:
                client = RpcClient(address)
                self._clients[address] = client
            return client

    def invalidate(self, address: Address):
        with self._lock:
            client = self._clients.pop(tuple(address), None)
        if client is not None:
            EventLoopThread.get().call_soon(client.close())
