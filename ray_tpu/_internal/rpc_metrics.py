"""RPC/transport observatory (reference: src/ray/rpc/ metrics +
common/asio event-loop instrumentation).

Every observability plane rides the RPC layer; this module gives the
layer itself eyes: per-method client/server latency histograms,
in-flight gauges, byte/retry/transport-error/chaos counters, a slow-RPC
watchdog ring with creation-site attribution, and the native-ring stats
export (src/fastrpc.cpp `frpc_ring_stats`).

Kill switch: ``RTPU_NO_RPC_METRICS=1`` -> :func:`enabled` is False,
no series is ever constructed, the watchdog ring never exists, and the
wire layer sends exact-legacy frames (no FLAG_META trace propagation) —
mixed on/off processes interoperate.

Separate namespace from ``runtime_metrics`` on purpose: the kill switch
must guarantee ZERO new series, so these metrics cannot live in the
always-built runtime namespace.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from collections import deque
from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Tuple

from .config import CONFIG

logger = logging.getLogger(__name__)

# Methods that never get a control-plane span and never trigger a
# SLOW_RPC event post: the span/event recorders call these very methods
# (add_task_events / add_event / add_alert), so instrumenting them would
# recurse; the rest are high-rate housekeeping whose spans would drown
# the trace tree (heartbeats, pubsub, metric flushes).
NO_SPAN_METHODS = frozenset({
    "add_task_events", "add_event", "add_alert",
    "heartbeat", "ping", "pubsub_message", "subscribe",
    "kv_put", "kv_get", "kv_del", "kv_keys",
    "get_rpc_stats", "report_metrics",
})

_SECONDS_BOUNDARIES = [
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0]


def _build_rpc_metrics() -> SimpleNamespace:
    from ..util.metrics import Counter, Gauge, Histogram
    return SimpleNamespace(
        client_seconds=Histogram(
            "rtpu_rpc_client_seconds",
            "Client-observed RPC latency by method (1/64 sampled; "
            "calls over rpc_slow_call_s always recorded)",
            boundaries=_SECONDS_BOUNDARIES,
            tag_keys=("method",)),
        server_seconds=Histogram(
            "rtpu_rpc_server_seconds",
            "Server handler latency by method (1/64 sampled; "
            "handlers over rpc_slow_call_s always recorded)",
            boundaries=_SECONDS_BOUNDARIES,
            tag_keys=("method",)),
        # pid tag: per-process gauge — cross-process merge is
        # last-write-wins per tag tuple (see runtime_metrics).
        inflight=Gauge(
            "rtpu_rpc_inflight",
            "RPC calls currently in flight in this process "
            "(dir=client: issued, awaiting reply; dir=server: "
            "handler running)",
            tag_keys=("pid", "dir")),
        bytes_total=Counter(
            "rtpu_rpc_bytes_total",
            "Wire bytes by method and direction (client requests "
            "out / replies in, server requests in / replies out)",
            tag_keys=("method", "dir")),
        retries=Counter(
            "rtpu_rpc_retries_total",
            "Backoff-mediated retries, by call site (every "
            "Backoff constructed with site= reports here)",
            tag_keys=("site",)),
        transport_errors=Counter(
            "rtpu_rpc_transport_errors_total",
            "Transport-level call failures (connection lost/refused, "
            "deadline, send failure) by method — per attempt, so a "
            "retried call counts each failed leg",
            tag_keys=("method",)),
        slow_calls=Counter(
            "rtpu_rpc_slow_calls_total",
            "Client calls exceeding rpc_slow_call_s (every one lands "
            "in the slow-RPC watchdog ring with attribution)",
            tag_keys=("method",)),
        chaos_hits=Counter(
            "rtpu_chaos_hits_total",
            "Armed chaos-rule activations by method pattern and "
            "action (drop_req / drop_resp / delay / dup)",
            tag_keys=("method", "action")),
        # Native-ring stats (src/fastrpc.cpp frpc_ring_stats): counters
        # are deltas of the C core's cumulative relaxed-atomic totals,
        # exported on the metrics flush cadence; gauges are the live
        # values. ring tag = ring index within the process.
        ring_frames=Counter(
            "rtpu_ring_frames_total",
            "Frames through a native ring by direction",
            tag_keys=("pid", "ring", "dir")),
        ring_bytes=Counter(
            "rtpu_ring_bytes_total",
            "Frame bytes through a native ring by direction",
            tag_keys=("pid", "ring", "dir")),
        ring_decode=Counter(
            "rtpu_ring_decode_total",
            "In-ring native decode outcomes (hit = decoded record "
            "delivered, fallback = passthrough while decode armed)",
            tag_keys=("pid", "ring", "result")),
        ring_fold_batches=Counter(
            "rtpu_ring_fold_batches_total",
            "Decref fold batches delivered by a native ring",
            tag_keys=("pid", "ring")),
        ring_wakeups=Counter(
            "rtpu_ring_notify_wakeups_total",
            "Python loop wakeups signalled by a native ring (one "
            "wakeup drains a whole batch of frames)",
            tag_keys=("pid", "ring")),
        ring_depth=Gauge(
            "rtpu_ring_queue_depth",
            "Events currently queued in a native ring awaiting the "
            "Python drain",
            tag_keys=("pid", "ring")),
        ring_depth_hwm=Gauge(
            "rtpu_ring_depth_hwm",
            "High-water mark of a native ring's event queue since "
            "process start",
            tag_keys=("pid", "ring")),
    )


# Lazy namespace, same pattern as runtime_metrics — but behind the kill
# switch: metrics() returns None when disabled, and _build only runs on
# the first *enabled* use, so RTPU_NO_RPC_METRICS=1 constructs nothing.
_NS_LOCK = threading.Lock()
_NS: Optional[SimpleNamespace] = None
_ENABLED: Optional[bool] = None
_PID: Optional[str] = None


def enabled() -> bool:
    """Kill-switch gate, cached after first read (the flag is a
    process-lifetime A/B arm; tests flip it via _reset_for_tests)."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = not bool(CONFIG.no_rpc_metrics)
    return _ENABLED


def metrics() -> Optional[SimpleNamespace]:
    global _NS
    if not enabled():
        return None
    if _NS is None:
        with _NS_LOCK:
            if _NS is None:
                _NS = _build_rpc_metrics()
    return _NS


def _pid() -> str:
    global _PID
    if _PID is None:
        _PID = str(os.getpid())
    return _PID


def _reset_for_tests():
    """Drop every cached singleton so a test can flip the kill switch
    or re-seed the watchdog. NOT for production use: re-building the
    namespace re-registers the series (evicting prior objects)."""
    global _NS, _ENABLED, _PID, _WATCHDOG, _RING_LAST
    with _NS_LOCK:
        _NS = None
        _ENABLED = None
        _PID = None
    with _WATCHDOG_LOCK:
        _WATCHDOG = None
    with _INFLIGHT_LOCK:
        _INFLIGHT["client"] = 0
        _INFLIGHT["server"] = 0
    with _BYTES_LOCK:
        _BYTES.clear()
    _RING_LAST = {}


# ---------------------------------------------------------------------------
# hot-path accumulators (in-flight + wire bytes)
#
# These two run on EVERY rpc (4x each per request/response round trip),
# so they must not touch the metric registry inline: a tagged set()/
# inc() costs a dict merge + tag validation + lock per call, which
# benched at ~35% overhead on a loopback echo. Instead the hot path
# does a plain dict update under a cheap lock and export_transport()
# folds the totals into the registry on the metrics flush cadence —
# the same deferred pattern the native-ring stats already use.
# ---------------------------------------------------------------------------

_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT = {"client": 0, "server": 0}

_BYTES_LOCK = threading.Lock()
# (method, dir) -> bytes accumulated since the last export_transport().
_BYTES: Dict[Tuple[str, str], int] = {}


def inflight_delta(direction: str, delta: int):
    if not enabled():
        return
    with _INFLIGHT_LOCK:
        value = _INFLIGHT[direction] + delta
        if value < 0:
            value = 0
        _INFLIGHT[direction] = value


def note_bytes(method: str, direction: str, nbytes: int):
    """Account wire bytes for one frame (dir in {"in", "out"} from the
    caller's perspective). Registry fold is deferred to
    export_transport()."""
    if not enabled():
        return
    key = (method, direction)
    with _BYTES_LOCK:
        _BYTES[key] = _BYTES.get(key, 0) + nbytes


def export_transport():
    """Fold the hot-path accumulators (wire bytes, in-flight counts)
    and the native-ring stats into the metric registry. Called from
    util.metrics.flush_now right before snapshotting, so every flush
    carries current totals; tests call it directly before asserting."""
    m = metrics()
    if m is None:
        return
    with _BYTES_LOCK:
        pending, drained = (_BYTES.copy(), True) if _BYTES else ({}, False)
        _BYTES.clear()
    if drained:
        for (method, direction), nbytes in pending.items():
            try:
                m.bytes_total.inc(nbytes, tags={"method": method,
                                                "dir": direction})
            except Exception:  # noqa: BLE001 — observability is best-effort
                logger.debug("bytes fold failed", exc_info=True)
    with _INFLIGHT_LOCK:
        inflight = dict(_INFLIGHT)
    for direction, value in inflight.items():
        try:
            m.inflight.set(value, tags={"pid": _pid(), "dir": direction})
        except Exception:  # noqa: BLE001 — observability is best-effort
            logger.debug("inflight fold failed", exc_info=True)
    export_ring_stats()


# ---------------------------------------------------------------------------
# frame meta (trace propagation)
# ---------------------------------------------------------------------------


def parse_meta(meta: bytes) -> Optional[Tuple[str, str]]:
    """Frame meta -> (trace_id, span_id), or None on any malformation
    (meta is advisory: a bad one must never fail the request)."""
    try:
        trace_id, _, span_id = meta.decode("utf-8", "replace") \
            .partition(":")
        if trace_id and span_id:
            return trace_id, span_id
    except Exception:  # noqa: BLE001 — advisory field
        logger.debug("unparseable frame meta", exc_info=True)
    return None


# ---------------------------------------------------------------------------
# slow-RPC watchdog
# ---------------------------------------------------------------------------

# Frames from these files are the transport itself, not the caller —
# the watchdog walks past them to attribute a slow call to the code
# that issued it.
_TRANSPORT_FILES = ("rpc.py", "rpc_metrics.py", "gcs_client.py",
                    "tasks.py", "aio.py")


def _caller_site() -> str:
    """Nearest stack frame outside the transport layer, as file:line.
    Bounded walk — a slow call is already >=1s, the walk is noise."""
    try:
        f = sys._getframe(3)
    except ValueError:
        return ""
    for _ in range(16):
        if f is None:
            break
        filename = f.f_code.co_filename
        base = os.path.basename(filename)
        if base not in _TRANSPORT_FILES \
                and not base.startswith(("asyncio", "base_events",
                                         "events", "tasks", "futures")):
            return f"{base}:{f.f_lineno}"
        f = f.f_back
    return ""


class SlowRpcWatchdog:
    """Bounded ring of slow client calls (method + peer + duration +
    creation site) plus a rate-limited ``SLOW_RPC`` GCS event so one
    slow peer shows up cluster-wide without an event flood."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: deque = deque(
            maxlen=max(1, int(CONFIG.rpc_slow_ring_size)))
        self._last_event = 0.0
        self.total = 0

    def note(self, method: str, peer: str, duration_s: float):
        row = {
            "ts": time.time(),
            "method": method,
            "peer": peer,
            "duration_s": round(float(duration_s), 6),
            "site": _caller_site(),
            "pid": os.getpid(),
        }
        emit = False
        with self._lock:
            self._ring.append(row)
            self.total += 1
            if method not in NO_SPAN_METHODS:
                now = time.monotonic()
                if now - self._last_event >= float(
                        CONFIG.rpc_slow_event_interval_s):
                    self._last_event = now
                    emit = True
        m = metrics()
        if m is not None:
            try:
                m.slow_calls.inc(tags={"method": method})
            except Exception:  # noqa: BLE001 — observability is best-effort
                logger.debug("slow-call metric bump failed", exc_info=True)
        if emit:
            self._emit_event(row)

    def _emit_event(self, row: Dict[str, Any]):
        try:
            from .core_worker import try_get_core_worker
            worker = try_get_core_worker()
            if worker is None:
                return
            worker.loop_post(worker.gcs.call(
                "add_event", event_type="SLOW_RPC",
                message=(f"slow RPC {row['method']} to {row['peer']}: "
                         f"{row['duration_s']:.3f}s"
                         + (f" (from {row['site']})" if row["site"]
                            else "")),
                severity="WARNING",
                fields={"method": row["method"], "peer": row["peer"],
                        "duration_s": row["duration_s"],
                        "site": row["site"], "pid": row["pid"]}))
        except Exception:  # noqa: BLE001 — observability is best-effort
            logger.debug("SLOW_RPC event post failed", exc_info=True)

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            rows = list(self._ring)
        if limit is not None and limit > 0:
            rows = rows[-limit:]
        return rows


_WATCHDOG_LOCK = threading.Lock()
_WATCHDOG: Optional[SlowRpcWatchdog] = None


def watchdog() -> Optional[SlowRpcWatchdog]:
    """The process watchdog singleton, or None when the observatory is
    disabled (the ring is never constructed under the kill switch)."""
    global _WATCHDOG
    if not enabled():
        return None
    if _WATCHDOG is None:
        with _WATCHDOG_LOCK:
            if _WATCHDOG is None:
                _WATCHDOG = SlowRpcWatchdog()
    return _WATCHDOG


# ---------------------------------------------------------------------------
# native-ring stats export (piggybacks on the metrics flush cadence)
# ---------------------------------------------------------------------------

# Field order fixed by src/fastrpc.cpp frpc_ring_stats.
RING_STAT_FIELDS = (
    "frames_in", "frames_out", "bytes_in", "bytes_out",
    "decode_hits", "decode_fallbacks", "fold_batches",
    "notify_wakeups", "queue_depth", "depth_hwm")

# (ring, field) -> last cumulative value seen, for counter deltas.
_RING_LAST: Dict[Tuple[int, str], int] = {}


def collect_ring_stats() -> List[Dict[str, int]]:
    """Live per-ring stats dicts from the native core (empty when the
    native library never loaded in this process). Read path only — no
    metric series touched, usable under the kill switch (cli/state
    surfaces still show ring health)."""
    mod = sys.modules.get("ray_tpu._native.fastrpc")
    if mod is None:
        return []
    try:
        rows = []
        for ring_idx, io in mod.NativeIO.all_instances():
            stats = io.ring_stats()
            if stats is not None:
                stats["ring"] = ring_idx
                rows.append(stats)
        return rows
    except Exception:  # noqa: BLE001 — observability is best-effort
        logger.debug("ring-stats read failed", exc_info=True)
        return []


def export_ring_stats():
    """Fold the C core's cumulative per-ring totals into the metric
    registry: counters advance by delta since the previous export,
    gauges take the live value. Called from util.metrics.flush_now via
    a sys.modules guard (processes that never imported this pay
    nothing)."""
    m = metrics()
    if m is None:
        return
    for stats in collect_ring_stats():
        ring = str(stats["ring"])
        tags = {"pid": _pid(), "ring": ring}
        try:
            for field, counter, extra in (
                    ("frames_in", m.ring_frames, {"dir": "in"}),
                    ("frames_out", m.ring_frames, {"dir": "out"}),
                    ("bytes_in", m.ring_bytes, {"dir": "in"}),
                    ("bytes_out", m.ring_bytes, {"dir": "out"}),
                    ("decode_hits", m.ring_decode, {"result": "hit"}),
                    ("decode_fallbacks", m.ring_decode,
                     {"result": "fallback"}),
                    ("fold_batches", m.ring_fold_batches, {}),
                    ("notify_wakeups", m.ring_wakeups, {})):
                value = int(stats.get(field, 0))
                key = (stats["ring"], field)
                delta = value - _RING_LAST.get(key, 0)
                _RING_LAST[key] = value
                if delta > 0:
                    counter.inc(delta, tags=dict(tags, **extra))
            m.ring_depth.set(int(stats.get("queue_depth", 0)), tags=tags)
            m.ring_depth_hwm.set(int(stats.get("depth_hwm", 0)),
                                 tags=tags)
        except Exception:  # noqa: BLE001 — observability is best-effort
            logger.debug("ring-stats export failed", exc_info=True)


# ---------------------------------------------------------------------------
# per-process stats view (the get_rpc_stats handler's payload)
# ---------------------------------------------------------------------------


def local_stats() -> Dict[str, Any]:
    """This process's transport view: counter totals, the slow-call
    ring, and live native-ring stats. Works (degraded to ring stats
    only) under the kill switch."""
    out: Dict[str, Any] = {
        "pid": os.getpid(),
        "enabled": enabled(),
        "rings": collect_ring_stats(),
        "slow": [],
        "slow_total": 0,
        "transport_errors": 0,
        "retries": 0,
    }
    with _INFLIGHT_LOCK:
        out["inflight"] = dict(_INFLIGHT)
    wd = _WATCHDOG
    if wd is not None:
        out["slow"] = wd.snapshot(limit=64)
        out["slow_total"] = wd.total
    ns = _NS
    if ns is not None:
        try:
            out["transport_errors"] = sum(
                v for _t, v in _series_pairs(ns.transport_errors))
            out["retries"] = sum(
                v for _t, v in _series_pairs(ns.retries))
        except Exception:  # noqa: BLE001
            logger.debug("counter total read failed", exc_info=True)
    return out


def _series_pairs(metric):
    snap = metric.snapshot()
    for tags, value in snap.get("series") or []:
        yield tuple(tags), value
