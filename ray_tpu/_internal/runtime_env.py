"""Runtime environments: per-task/actor working_dir, py_modules, env_vars
(reference: python/ray/_private/runtime_env/ — the agent
agent/runtime_env_agent.py:165,298 creates envs per URI; working_dir/
py_modules packaging packaging.py; URI cache uri_cache.py).

Design (agentless): the driver packages local directories into
content-hashed zips stored in the GCS KV (`gcs://<sha>` URIs — the KV is
the small-package store, like the reference's GCS-backed packages up to
100MB); workers extract each URI once into a per-session cache directory
and prepend it to sys.path (py_modules) or chdir into it (working_dir).
env_vars are applied at worker spawn via the env-keyed worker pool, so a
worker process never mixes environments."""

from __future__ import annotations

import hashlib
import io
import logging
import os
import re
import threading
import sys
import zipfile
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

PACKAGE_KV_NS = "runtime_env_packages"
MAX_PACKAGE_BYTES = 100 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def package_directory(path: str) -> Tuple[str, bytes]:
    """Zip a directory deterministically; returns (uri, zip_bytes)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env path {path!r} is not a directory")
    buf = io.BytesIO()
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for name in sorted(files):
            full = os.path.join(root, name)
            entries.append((os.path.relpath(full, path), full))
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel, full in entries:
            info = zipfile.ZipInfo(rel)  # fixed date -> stable hash
            with open(full, "rb") as f:
                zf.writestr(info, f.read())
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(limit {MAX_PACKAGE_BYTES}); exclude large artifacts")
    digest = hashlib.sha256(data).hexdigest()[:24]
    return f"gcs://{digest}", data


# abspath -> (dir signature, uploaded uri): avoid re-zipping per submission
_upload_cache: Dict[str, Tuple[Tuple, str]] = {}
_upload_lock = threading.Lock()


def _dir_signature(path: str) -> Tuple:
    count, newest, total = 0, 0.0, 0
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
        for name in files:
            try:
                st = os.stat(os.path.join(root, name))
            except OSError:
                continue
            count += 1
            newest = max(newest, st.st_mtime)
            total += st.st_size
    return (count, newest, total)


def upload_packages(runtime_env: Optional[Dict[str, Any]], gcs
                    ) -> Dict[str, Any]:
    """Driver-side: replace local paths with content-addressed URIs,
    uploading each package once (reference: packaging.upload_package_if_
    needed + uri_cache)."""
    if not runtime_env:
        return {}
    out = dict(runtime_env)

    def upload(path: str) -> str:
        path = os.path.abspath(path)
        sig = _dir_signature(path)
        with _upload_lock:
            cached = _upload_cache.get(path)
            if cached is not None and cached[0] == sig:
                return cached[1]
        uri, data = package_directory(path)
        key = uri.split("://", 1)[1]
        if not gcs.call_sync("kv_exists", ns=PACKAGE_KV_NS, key=key):
            gcs.put(PACKAGE_KV_NS, key, data)
        with _upload_lock:
            _upload_cache[path] = (sig, uri)
        return uri

    working_dir = out.get("working_dir")
    if working_dir and not working_dir.startswith("gcs://"):
        out["working_dir"] = upload(working_dir)
    modules = out.get("py_modules")
    if modules:
        out["py_modules"] = [
            m if m.startswith("gcs://") else upload(m) for m in modules]
    pip = out.get("pip")
    if pip:
        # Zero-egress environments cannot create venvs; the contract here
        # is "verify importable, else fail fast" (documented limitation).
        out["pip"] = list(pip)
    conda = out.get("conda")
    if isinstance(conda, str) and conda.endswith((".yml", ".yaml")):
        # environment.yml exists on the DRIVER's disk only: inline it
        # now so raylets on other nodes (where runtime_env_key re-runs)
        # never need the file.
        out["conda"] = _load_yaml(conda)
    return out


class RuntimeEnvManager:
    """Worker-side URI cache + activation
    (reference: uri_cache.py + working_dir/py_modules plugins)."""

    def __init__(self, cache_root: str):
        self._root = cache_root
        self._lock = threading.Lock()
        self._ready: Dict[str, str] = {}  # uri -> extracted dir

    def _fetch_and_extract(self, uri: str, gcs) -> str:
        with self._lock:
            path = self._ready.get(uri)
        if path is not None:
            return path
        key = uri.split("://", 1)[1]
        target = os.path.join(self._root, key)
        if not os.path.isdir(target):
            data = gcs.get(PACKAGE_KV_NS, key)
            if data is None:
                raise RuntimeError(f"runtime_env package {uri} not found")
            # The cache dir is shared by every worker process on the node;
            # stage into a per-process unique dir, then rename — losers of
            # the race just discard their copy.
            import shutil
            import tempfile
            os.makedirs(self._root, exist_ok=True)
            tmp = tempfile.mkdtemp(prefix=f".{key}-", dir=self._root)
            with zipfile.ZipFile(io.BytesIO(data)) as zf:
                zf.extractall(tmp)
            try:
                os.rename(tmp, target)
            except OSError:
                if os.path.isdir(target):  # someone else won
                    shutil.rmtree(tmp, ignore_errors=True)
                else:
                    raise
        with self._lock:
            self._ready[uri] = target
        return target

    def apply(self, runtime_env: Dict[str, Any], gcs):
        """Activate working_dir/py_modules/pip in THIS worker process."""
        import sys
        if not runtime_env:
            return
        for uri in runtime_env.get("py_modules") or []:
            path = self._fetch_and_extract(uri, gcs)
            if path not in sys.path:
                sys.path.insert(0, path)
        working_dir = runtime_env.get("working_dir")
        if working_dir:
            path = self._fetch_and_extract(working_dir, gcs)
            if path not in sys.path:
                sys.path.insert(0, path)
            os.chdir(path)
        for req in runtime_env.get("pip") or []:
            module = req.split("==")[0].split(">=")[0].strip()
            module = {"pyyaml": "yaml", "pillow": "PIL"}.get(
                module.lower(), module).replace("-", "_")
            try:
                __import__(module)
            except ImportError as e:
                raise RuntimeError(
                    f"runtime_env pip requirement {req!r} is not available "
                    "in this zero-egress image (packages cannot be "
                    "installed at runtime; bake them into the image)"
                ) from e


# ---------------------------------------------------------------------------
# Isolated python environments (reference: _private/runtime_env/conda.py /
# uv.py — a per-requirements interpreter env; here a venv with
# system-site-packages, which in a zero-egress image validates/overlays
# requirements against the baked packages instead of downloading)
# ---------------------------------------------------------------------------

def python_env_key(requirements: List[str]) -> str:
    digest = hashlib.sha256(
        "\n".join(sorted(requirements)).encode()).hexdigest()[:16]
    return f"pyenv-{digest}"


def _locked_build(env_dir: str, build_fn,
                  build_timeout_s: float = 300.0) -> None:
    """Run `build_fn()` exactly once per env_dir across processes AND
    threads: marker short-circuits, a lockfile elects one builder
    (stale locks from SIGKILLed builders are reclaimed), losers wait
    for the marker. Partial builds from a crashed builder are cleared
    before rebuilding (conda/uv error on existing prefixes).

    `build_timeout_s` must cover the slowest legitimate build for this
    env kind (conda env create can take many minutes): the waiter
    deadline derives from it.

    Builder election is an flock(LOCK_EX|LOCK_NB) on a shared lock
    file: the kernel releases the lock when the holder dies (any way,
    including SIGKILL), so there is NO staleness heuristic and no
    reclaim race — a waiter that later wins the flock and still sees no
    marker simply becomes the next builder of the crashed build."""
    import fcntl
    import shutil
    import time as _time

    marker = os.path.join(env_dir, ".rtpu-ready")
    if os.path.exists(marker):
        return
    os.makedirs(os.path.dirname(env_dir), exist_ok=True)
    lock_path = env_dir + ".lock"
    fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)
    try:
        # Sized for TWO sequential builds: if the first builder dies
        # mid-build, a waiter takes over and rebuilds from scratch —
        # the deadline only ever fires while some OTHER process holds
        # the flock (a waiter that wins the lock builds regardless).
        deadline = _time.monotonic() + 2 * build_timeout_s + 120
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                # a live builder holds the lock: wait for its marker
                if os.path.exists(marker):
                    return
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"python env {env_dir} build did not finish")
                _time.sleep(0.25)
                continue
            # we hold the lock — either first builder, or the previous
            # builder died (kernel released) / finished (marker set)
            if os.path.exists(marker):
                return
            if os.path.isdir(env_dir):  # crashed builder's partial env
                shutil.rmtree(env_dir, ignore_errors=True)
            build_fn()
            with open(marker, "w") as f:
                f.write("ok")
            return
    finally:
        os.close(fd)  # releases the flock if held


def ensure_python_env(requirements: List[str], root: str) -> str:
    """Create (once) an isolated venv for `requirements`; returns its
    python executable. Safe under concurrent callers via _locked_build.
    """
    import subprocess
    import sys

    env_dir = os.path.join(root, python_env_key(requirements))
    py = os.path.join(env_dir, "bin", "python")

    def build():
        import venv
        venv.create(env_dir, system_site_packages=True, with_pip=True,
                    clear=True)
        # The launching interpreter may itself be a venv (its packages
        # are NOT the base python's "system site"): link its
        # site-packages into the new env so baked packages satisfy
        # requirements offline (reference: conda.py inherits the base
        # env's packages the same way).
        import glob as _glob
        import site as _site
        env_sites = _glob.glob(os.path.join(
            env_dir, "lib", "python*", "site-packages"))
        parent_sites = [p for p in _site.getsitepackages()
                        if os.path.isdir(p)]
        for env_site in env_sites:
            with open(os.path.join(env_site, "_rtpu_parent.pth"),
                      "w") as f:
                f.write("\n".join(parent_sites) + "\n")
        if requirements:
            req_file = os.path.join(env_dir, "requirements.txt")
            with open(req_file, "w") as f:
                f.write("\n".join(requirements) + "\n")
            # Zero-egress friendly: requirements already satisfied by the
            # system site pass instantly; anything else fails loudly.
            proc = subprocess.run(
                [py, "-m", "pip", "install", "--no-index",
                 "-r", req_file],
                capture_output=True, timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    "python_env requirements not satisfiable offline:\n"
                    + proc.stderr.decode()[-2000:])

    _locked_build(env_dir, build, build_timeout_s=600.0)
    return py


# ---------------------------------------------------------------------------
# conda / uv environments (reference: _private/runtime_env/conda.py and
# uv.py — per-requirements interpreter environments managed by the named
# tool). TPU-native deployment note: production TPU images are
# zero-egress and usually lack conda; when the tool binary is absent,
# python-level dependencies fall back to the same offline overlay-venv
# as `pip` (validate against baked packages), and binary/channel deps
# fail loudly.
# ---------------------------------------------------------------------------

def parse_conda_spec(conda: Any) -> Tuple[Optional[str], List[str]]:
    """Normalize the `conda` runtime_env field -> (env_name, pip_deps).

    Accepts the reference's three shapes (conda.py:get_conda_dict): a
    named existing env (str), a path to environment.yml (str ending
    .yml/.yaml), or an inline environment dict. Inline/file deps are
    flattened to pip-style requirements: "numpy=1.26" -> "numpy==1.26",
    nested {"pip": [...]} lists pass through."""
    if isinstance(conda, str):
        if conda.endswith((".yml", ".yaml")):
            spec = _load_yaml(conda)
        else:
            return conda, []
    elif isinstance(conda, dict):
        spec = conda
    else:
        raise ValueError(f"runtime_env conda must be str|dict, got {conda!r}")
    deps: List[str] = []
    for dep in spec.get("dependencies", []):
        if isinstance(dep, dict):
            deps.extend(dep.get("pip", []))
        elif isinstance(dep, str):
            req = dep.strip()
            name = re.split(r"[=<>~!]", req, 1)[0].strip()
            if name in ("python", "pip"):
                continue  # interpreter/tool pins are the env's business
            # conda's single-= pin becomes pip's ==; real specifiers
            # (>=, <=, ~=, ==, !=) pass through untouched
            req = re.sub(r"(?<![=<>~!])=(?![=<>~!])", "==", req, count=1)
            deps.append(req)
    return None, deps


def _load_yaml(path: str) -> Dict[str, Any]:
    try:
        import yaml
        with open(path) as f:
            return yaml.safe_load(f) or {}
    except ImportError as e:
        raise RuntimeError(
            f"conda environment file {path!r} needs pyyaml") from e


def _find_conda_env_python(name: str) -> Optional[str]:
    """Interpreter of an EXISTING conda env by name (no conda needed at
    runtime if the env is already materialized on disk)."""
    roots = []
    exe = os.environ.get("CONDA_EXE")
    if exe:
        roots.append(os.path.join(os.path.dirname(os.path.dirname(exe)),
                                  "envs"))
    prefix = os.environ.get("CONDA_PREFIX")
    if prefix:
        base = os.path.dirname(prefix) if os.path.basename(
            os.path.dirname(prefix)) == "envs" else prefix
        roots.append(os.path.join(base, "envs"))
    home = os.path.expanduser("~")
    roots += [os.path.join(home, d, "envs")
              for d in ("miniconda3", "anaconda3", "mambaforge",
                        ".conda")]
    for root in roots:
        py = os.path.join(root, name, "bin", "python")
        if os.path.exists(py):
            return py
    return None


def ensure_conda_env_entry(entry: Tuple, root: str) -> str:
    """Interpreter for a normalized conda key entry (("env", name) or
    ("deps", *pip_style_deps) — see task_spec._conda_entry). Named env
    -> its python (must already exist). Deps -> `conda env create` when
    conda is installed; otherwise the offline overlay venv over the
    spec's python-level deps."""
    import shutil
    import subprocess
    name = entry[1] if entry[0] == "env" else None
    deps = list(entry[1:]) if entry[0] == "deps" else []
    if name is not None:
        py = _find_conda_env_python(name)
        if py is not None:
            return py
        raise RuntimeError(
            f"conda env {name!r} not found on this node (looked under "
            "CONDA_EXE/CONDA_PREFIX/~/*conda*/envs)")
    conda_bin = shutil.which("conda") or shutil.which("mamba")
    if conda_bin:
        digest = hashlib.sha256(
            repr(sorted(deps)).encode()).hexdigest()[:16]
        env_dir = os.path.join(root, f"conda-{digest}")
        py = os.path.join(env_dir, "bin", "python")

        def build():
            spec_path = os.path.join(root, f"conda-{digest}.yml")
            with open(spec_path, "w") as f:
                f.write("dependencies:\n- python\n- pip\n- pip:\n")
                for d in deps:
                    f.write(f"  - {d}\n")
            proc = subprocess.run(
                [conda_bin, "env", "create", "-q", "-p", env_dir,
                 "-f", spec_path],
                capture_output=True, timeout=1800)
            if proc.returncode != 0:
                raise RuntimeError("conda env create failed:\n"
                                   + proc.stderr.decode()[-2000:])

        _locked_build(env_dir, build, build_timeout_s=1800.0)
        return py
    # zero-egress / conda-less node: same offline contract as `pip`
    return ensure_python_env(deps, root)


def normalize_uv(uv: Any) -> List[str]:
    """`uv` runtime_env field -> package list (reference uv.py accepts
    a list or {"packages": [...]})."""
    if isinstance(uv, dict):
        uv = uv.get("packages", [])
    if not isinstance(uv, (list, tuple)):
        raise ValueError(f"runtime_env uv must be list|dict, got {uv!r}")
    return list(uv)


def _unsatisfied_in_env(py: str, packages: List[str]) -> List[str]:
    """Requirements from `packages` NOT already importable/installed in
    the interpreter `py` (== pins checked exactly; other specifiers
    satisfied-if-present, matching the pip overlay's offline contract)."""
    import subprocess
    probe = (
        "import importlib.metadata as md, sys\n"
        "for line in sys.stdin.read().splitlines():\n"
        "    req = line.strip()\n"
        "    name = req\n"
        "    pin = None\n"
        "    for sep in ('==', '>=', '<=', '~=', '>', '<'):\n"
        "        if sep in req:\n"
        "            name, _, rest = req.partition(sep)\n"
        "            pin = rest if sep == '==' else None\n"
        "            break\n"
        "    name = name.strip().split('[')[0]\n"
        "    try:\n"
        "        ver = md.version(name)\n"
        "    except md.PackageNotFoundError:\n"
        "        print(req)\n"
        "        continue\n"
        "    if pin is not None and ver != pin.strip():\n"
        "        print(req)\n")
    proc = subprocess.run([py, "-c", probe], input="\n".join(packages),
                          capture_output=True, text=True, timeout=120)
    if proc.returncode != 0:
        return list(packages)
    return [line for line in proc.stdout.splitlines() if line.strip()]


def ensure_uv_env(packages: List[str], root: str) -> str:
    """Interpreter for a uv runtime env: `uv venv` + offline
    `uv pip install` when uv is installed, else the overlay venv."""
    import shutil
    import subprocess
    uv_bin = shutil.which("uv")
    if not uv_bin:
        return ensure_python_env(list(packages), root)
    digest = hashlib.sha256(
        "\n".join(sorted(packages)).encode()).hexdigest()[:16]
    env_dir = os.path.join(root, f"uv-{digest}")
    py = os.path.join(env_dir, "bin", "python")

    def build():
        proc = subprocess.run(
            [uv_bin, "venv", "--python", sys.executable,
             "--system-site-packages", env_dir],
            capture_output=True, timeout=300)
        if proc.returncode != 0:
            raise RuntimeError("uv venv failed:\n"
                               + proc.stderr.decode()[-2000:])
        # "system site" resolves to the BASE interpreter's site — when
        # the launcher is itself a venv (this image), its packages
        # wouldn't be visible. Link them in, same as ensure_python_env.
        import glob as _glob
        import site as _site
        parent_sites = [p for p in _site.getsitepackages()
                        if os.path.isdir(p)]
        for env_site in _glob.glob(os.path.join(
                env_dir, "lib", "python*", "site-packages")):
            with open(os.path.join(env_site, "_rtpu_parent.pth"),
                      "w") as f:
                f.write("\n".join(parent_sites) + "\n")
        missing = _unsatisfied_in_env(py, packages) if packages else []
        if missing:
            # Only genuinely-missing packages go through uv's resolver
            # — its offline mode does not consult the system site
            # overlay, so baked packages must be filtered out first.
            proc = subprocess.run(
                [uv_bin, "pip", "install", "--python", py, "--offline",
                 *missing],
                capture_output=True, timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    "uv pip install (offline) failed — zero-egress "
                    "images must bake packages:\n"
                    + proc.stderr.decode()[-2000:])

    _locked_build(env_dir, build, build_timeout_s=600.0)
    return py


# ---------------------------------------------------------------------------
# Container runtime env (reference: _private/runtime_env/container/ —
# image_uri runs the worker inside a container; podman in the reference,
# any docker-compatible runtime here)
# ---------------------------------------------------------------------------

def find_container_runtime() -> Optional[str]:
    """First available container runtime. `RTPU_CONTAINER_RUNTIME`
    overrides (tests point it at a shim; production at podman/docker)."""
    import shutil
    override = os.environ.get("RTPU_CONTAINER_RUNTIME")
    if override:
        return override
    for candidate in ("podman", "docker"):
        path = shutil.which(candidate)
        if path:
            return path
    return None


def build_container_argv(image_uri: str, argv: List[str],
                         env: Dict[str, str], pkg_root: str,
                         extra_env_keys: Optional[List[str]] = None
                         ) -> List[str]:
    """Wrap a worker command to run inside `image_uri` (reference:
    container/container_manager.py assembles the same shape: host
    networking so the worker's RPC server is reachable, the framework
    source and session tmp mounted through, RTPU_*/JAX_* env forwarded).
    Raises RuntimeEnvSetupError when no container runtime exists —
    deterministic, so the lease is rejected permanently."""
    from .errors import RuntimeEnvSetupError
    runtime = find_container_runtime()
    if runtime is None:
        raise RuntimeEnvSetupError(
            f"runtime_env image_uri={image_uri!r} requires a container "
            "runtime (podman/docker) on the node; none found")
    out = [runtime, "run", "--rm", "--network=host",
           "-v", f"{pkg_root}:{pkg_root}:ro",
           "-v", "/tmp:/tmp",
           "-v", "/dev/shm:/dev/shm"]
    extra = set(extra_env_keys or ())
    for key, value in env.items():
        # framework env + the USER's runtime_env env_vars (extra) — the
        # latter would otherwise silently vanish inside the container
        if key in extra or key.startswith(("RTPU_", "JAX_", "PALLAS_",
                                           "XLA_", "PYTHON")):
            out += ["-e", f"{key}={value}"]
    out.append(image_uri)
    out += argv
    return out
