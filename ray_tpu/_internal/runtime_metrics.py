"""Core-runtime metric series (reference: src/ray/stats/metric_defs.cc —
the scheduler/object-store/task series the C++ stats layer exports).

Lazy singleton so importing core_worker/raylet has no side effects; the
first observation registers the series and starts the process's metrics
flusher. Every observation is a local dict update under an uncontended
lock — cheap enough for the submit hot path."""

from __future__ import annotations

from types import SimpleNamespace

from ..util.metrics import LazyMetrics

_LATENCY_BOUNDARIES = [
    0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0]


def _build() -> SimpleNamespace:
    from ..util.metrics import Counter, Gauge, Histogram
    return SimpleNamespace(
        lease_wait=Histogram(
            "rtpu_task_lease_wait_seconds",
            "Normal-task submit to lease grant (queueing + "
            "raylet round trips)",
            boundaries=_LATENCY_BOUNDARIES),
        push_roundtrip=Histogram(
            "rtpu_task_push_roundtrip_seconds",
            "Task push to reply on the leased worker "
            "(includes execution)",
            boundaries=_LATENCY_BOUNDARIES),
        # pid tag: per-process gauge — the cross-process
        # merge is last-write-wins per tag tuple, so an
        # untagged gauge would show one arbitrary driver's
        # backlog for the whole cluster
        pending_tasks=Gauge(
            "rtpu_tasks_pending",
            "Tasks pending in this process's TaskManager",
            tag_keys=("pid",)),
        store_put_bytes=Counter(
            "rtpu_object_store_put_bytes_total",
            "Bytes sealed into plasma by this process"),
        push_duplicates=Counter(
            "rtpu_push_duplicate_replies_total",
            "Duplicate task pushes answered from the "
            "completed-reply cache (re-execution avoided)"),
        push_recovered=Counter(
            "rtpu_push_reply_recovered_total",
            "Lost push replies recovered via the probe "
            "channel"),
        wire_task_bytes=Counter(
            "rtpu_task_wire_bytes_total",
            "Bytes of flat task frames (template deltas + "
            "actor-batch framing) shipped by this process; "
            "divide by submitted tasks for bytes/task"),
        raylet_lease_queue=Gauge(
            "rtpu_raylet_lease_queue_depth",
            "Lease requests queued at the raylet",
            tag_keys=("node",)),
        lease_reclaims=Counter(
            "rtpu_lease_reclaims_total",
            "Idle leases returned early by grant-time cross-shard "
            "reclaim (a peer shard's lease request was starving)"),
        # -- fleet operations (drain / rolling upgrades / elastic
        # autoscaler): queue age is the autoscaler's primary scale-up
        # signal, the draining gauge is the dashboard's drain indicator --
        lease_queue_age=Gauge(
            "rtpu_lease_queue_age_seconds",
            "Age of the oldest pending lease request queued at the "
            "raylet, per resource shape",
            tag_keys=("node", "shape")),
        node_draining=Gauge(
            "rtpu_node_draining",
            "1 while this raylet is fenced for a graceful drain "
            "(no new lease grants), else 0",
            tag_keys=("node",)),
        drains_completed=Counter(
            "rtpu_drains_total",
            "Graceful node drains completed, by outcome (clean = all "
            "leases returned in time; timeout = stragglers killed)",
            tag_keys=("node", "outcome")),
        drain_latency=Histogram(
            "rtpu_drain_seconds",
            "Fence-to-empty drain latency (in-flight leases returned "
            "or killed at the deadline)",
            boundaries=_LATENCY_BOUNDARIES,
            tag_keys=("node",)),
        autoscale_decisions=Counter(
            "rtpu_autoscale_decisions_total",
            "Elastic-autoscaler actions taken (launch / drain_in / "
            "terminate)",
            tag_keys=("action",)),
        raylet_leases_granted=Counter(
            "rtpu_raylet_leases_granted_total",
            "Worker leases granted by the raylet",
            tag_keys=("node",)),
        raylet_store_bytes=Gauge(
            "rtpu_raylet_object_store_bytes",
            "Bytes resident in the raylet's object store",
            tag_keys=("node",)),
        raylet_workers=Gauge(
            "rtpu_raylet_workers",
            "Worker processes in the raylet's pool",
            tag_keys=("node",)),
        # -- memory observability plane (reference: local_object_manager
        # pin/spill accounting + memory_monitor.h node RSS watch) --
        store_capacity=Gauge(
            "rtpu_store_capacity_bytes",
            "Configured object-store capacity on this node",
            tag_keys=("node",)),
        store_pinned_bytes=Gauge(
            "rtpu_store_pinned_bytes",
            "Bytes of store objects with a nonzero pin count",
            tag_keys=("node",)),
        store_spilled_bytes=Gauge(
            "rtpu_store_spilled_bytes",
            "Bytes currently spilled out of the store to "
            "disk/cloud",
            tag_keys=("node",)),
        store_spilled_total=Counter(
            "rtpu_store_spilled_bytes_total",
            "Cumulative bytes spilled out of the object store",
            tag_keys=("node",)),
        store_restored_total=Counter(
            "rtpu_store_restored_bytes_total",
            "Cumulative bytes restored from spill storage",
            tag_keys=("node",)),
        store_spill_latency=Histogram(
            "rtpu_store_spill_seconds",
            "Per-object spill latency",
            boundaries=_LATENCY_BOUNDARIES,
            tag_keys=("node",)),
        store_restore_latency=Histogram(
            "rtpu_store_restore_seconds",
            "Per-object restore latency",
            boundaries=_LATENCY_BOUNDARIES,
            tag_keys=("node",)),
        node_mem_used_ratio=Gauge(
            "rtpu_node_mem_used_ratio",
            "Used fraction of node system memory "
            "(/proc/meminfo, memory watchdog)",
            tag_keys=("node",)),
        owned_refs=Gauge(
            "rtpu_worker_owned_refs",
            "Entries in this process's reference table",
            tag_keys=("pid",)),
        # -- owner shards (the multi-loop driver core): imbalance across
        # shards shows up here — cli status / the dashboard node view
        # render these rows --
        shard_queue_depth=Gauge(
            "rtpu_owner_shard_queue_depth",
            "Outstanding owned work on one owner shard "
            "(pushed tasks awaiting replies + lease waiters "
            "+ undrained mailbox posts)",
            tag_keys=("pid", "shard")),
        shard_loop_lag=Gauge(
            "rtpu_owner_shard_loop_lag_seconds",
            "call_soon_threadsafe-to-run latency of one owner "
            "shard's io loop (probed on demand)",
            tag_keys=("pid", "shard")),
        shard_submit=Histogram(
            "rtpu_owner_shard_submit_seconds",
            "Driver-side submit_task cost per owner shard "
            "(refcount + pending bookkeeping + routing; 1/64 "
            "sampled, recorded only when >1 shard exists)",
            boundaries=[0.000001, 0.000005, 0.00001, 0.000025,
                        0.00005, 0.0001, 0.00025, 0.0005, 0.001,
                        0.0025, 0.005, 0.01],
            tag_keys=("shard",)),
        # -- log & forensics plane (per-worker rings at the raylet:
        # capture volume, every drop reason, resident ring bytes) --
        log_lines=Counter(
            "rtpu_log_lines_total",
            "Worker log lines captured into raylet rings",
            tag_keys=("node", "stream", "level")),
        log_dropped=Counter(
            "rtpu_log_dropped_lines_total",
            "Worker log lines dropped (ring_overflow / "
            "rate_limited / backpressure)",
            tag_keys=("node", "reason")),
        log_ring_bytes=Gauge(
            "rtpu_log_ring_bytes",
            "Bytes resident across this raylet's worker log rings",
            tag_keys=("node",)),
        # -- GCS durability & failover plane --
        gcs_failovers=Counter(
            "rtpu_gcs_failovers_total",
            "GCS recoveries from persisted state (restart with a "
            "prior incarnation on disk)"),
        gcs_wal_bytes=Counter(
            "rtpu_gcs_wal_bytes_total",
            "Bytes appended to the GCS write-ahead log"),
        gcs_persist_failures=Counter(
            "rtpu_gcs_persist_failures_total",
            "Failed GCS persist operations (WAL append / snapshot "
            "write) — nonzero means durability is degraded"),
        gcs_reconnects=Counter(
            "rtpu_gcs_reconnects_total",
            "Completed GCS reconnect cycles (client detected the GCS "
            "down, then re-registered on a live incarnation)",
            tag_keys=("component",)),
        gcs_reconnect_latency=Histogram(
            "rtpu_gcs_reconnect_seconds",
            "GCS-down detection to successful re-registration, per "
            "reconnecting component (raylet / driver)",
            boundaries=_LATENCY_BOUNDARIES,
            tag_keys=("component",)),
        # -- continuous profiler meta-metrics (the profiler profiles
        # itself: sample volume, ring overflow, per-pass overhead) --
        profiler_samples=Counter(
            "rtpu_profiler_samples_total",
            "Stack samples recorded by this process's sampler",
            tag_keys=("pid",)),
        profiler_dropped=Counter(
            "rtpu_profiler_dropped_samples_total",
            "Samples dropped on ring overflow (oldest evicted)",
            tag_keys=("pid",)),
        profiler_pass_seconds=Histogram(
            "rtpu_profiler_sample_pass_seconds",
            "Wall time of one sampling pass over all threads",
            boundaries=[0.00001, 0.00005, 0.0001, 0.00025, 0.0005,
                        0.001, 0.0025, 0.005, 0.01, 0.05],
            tag_keys=("pid",)),
    )


runtime_metrics = LazyMetrics(_build)
