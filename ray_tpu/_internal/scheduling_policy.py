"""Scheduling policies.

Equivalents of the reference's policy suite
(src/ray/raylet/scheduling/policy/): hybrid (default — prefer the local node
until its utilization crosses a threshold, then best-fit across the cluster),
spread, random, node-affinity, node-label, and bundle (placement-group gang)
strategies over a cluster resource view.

The view is a plain dict {node_id_hex: NodeView}; policies are pure functions
so both the GCS (actor/PG scheduling) and each raylet (lease spillback) reuse
them against whatever snapshot they hold.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from .config import CONFIG
from .resources import NodeResources, ResourceSet


@dataclass
class NodeView:
    node_id: str                    # hex
    resources: NodeResources
    alive: bool = True
    # draining nodes accept no new leases
    draining: bool = False
    # GCS cluster-view delta version (0 = never broadcast)
    ver: int = 0

    def feasible(self, demand: ResourceSet) -> bool:
        return demand.fits(self.resources.total)

    def available(self, demand: ResourceSet) -> bool:
        return demand.fits(self.resources.available)


def _schedulable(view: Mapping[str, NodeView]) -> List[NodeView]:
    return [n for n in view.values() if n.alive and not n.draining]


def pick_hybrid(view: Mapping[str, NodeView], demand: ResourceSet,
                local_node_id: str,
                label_selector: Optional[Mapping[str, str]] = None,
                threshold: Optional[float] = None) -> Optional[str]:
    """Default policy (reference: hybrid_scheduling_policy.h:50): stay local
    while local utilization < threshold and the task fits; otherwise pick the
    feasible node with the lowest utilization (best-fit by critical resource),
    breaking ties by node id for determinism."""
    threshold = (CONFIG.scheduler_hybrid_threshold
                 if threshold is None else threshold)
    nodes = _schedulable(view)
    if label_selector:
        nodes = [n for n in nodes
                 if n.resources.matches_labels(label_selector)]
    local = next((n for n in nodes if n.node_id == local_node_id), None)
    if (local is not None and local.available(demand)
            and local.resources.utilization() < threshold):
        return local.node_id
    candidates = [n for n in nodes if n.available(demand)]
    if candidates:
        return min(candidates,
                   key=lambda n: (n.resources.utilization(), n.node_id)).node_id
    feasible = [n for n in nodes if n.feasible(demand)]
    if feasible:
        # Queue on the least-loaded feasible node.
        return min(feasible,
                   key=lambda n: (n.resources.utilization(), n.node_id)).node_id
    return None


def pick_spread(view: Mapping[str, NodeView], demand: ResourceSet,
                spread_clock: int,
                label_selector: Optional[Mapping[str, str]] = None
                ) -> Optional[str]:
    """Round-robin across available nodes (reference: spread policy)."""
    nodes = sorted(_schedulable(view), key=lambda n: n.node_id)
    if label_selector:
        nodes = [n for n in nodes
                 if n.resources.matches_labels(label_selector)]
    avail = [n for n in nodes if n.available(demand)]
    pool = avail or [n for n in nodes if n.feasible(demand)]
    if not pool:
        return None
    return pool[spread_clock % len(pool)].node_id


def pick_random(view: Mapping[str, NodeView],
                demand: ResourceSet) -> Optional[str]:
    pool = [n for n in _schedulable(view) if n.available(demand)]
    return random.choice(pool).node_id if pool else None


def pick_node_affinity(view: Mapping[str, NodeView], demand: ResourceSet,
                       node_id: str, soft: bool) -> Optional[str]:
    node = view.get(node_id)
    if node is not None and node.alive and not node.draining \
            and node.feasible(demand):
        return node_id
    if soft:
        return pick_hybrid(view, demand, local_node_id=node_id)
    return None


def pick_node_label(view: Mapping[str, NodeView], demand: ResourceSet,
                    selector: Mapping[str, str]) -> Optional[str]:
    pool = [n for n in _schedulable(view)
            if n.resources.matches_labels(selector) and n.available(demand)]
    if pool:
        return min(pool, key=lambda n: (n.resources.utilization(),
                                        n.node_id)).node_id
    feas = [n for n in _schedulable(view)
            if n.resources.matches_labels(selector) and n.feasible(demand)]
    return min(feas, key=lambda n: n.node_id).node_id if feas else None


# ---------------------------------------------------------------------------
# Placement-group bundle placement (reference: bundle_scheduling_policy.cc)
# ---------------------------------------------------------------------------

def place_bundles(view: Mapping[str, NodeView],
                  bundles: Sequence[ResourceSet],
                  strategy: str) -> Optional[List[str]]:
    """Map each bundle to a node id, or None if infeasible now.

    PACK: minimize node count (greedy first-fit onto fewest nodes).
    SPREAD: best-effort one bundle per node, reusing nodes when short.
    STRICT_PACK: all bundles on one node.
    STRICT_SPREAD: all bundles on distinct nodes.
    """
    nodes = sorted(_schedulable(view), key=lambda n: n.node_id)
    # Work on a scratch copy of availability.
    scratch: Dict[str, ResourceSet] = {
        n.node_id: n.resources.available for n in nodes}

    def fits(nid: str, demand: ResourceSet) -> bool:
        return demand.fits(scratch[nid])

    def take(nid: str, demand: ResourceSet):
        scratch[nid] = scratch[nid] - demand

    if strategy == "STRICT_PACK":
        for n in nodes:
            if all_fit_one(scratch[n.node_id], bundles):
                return [n.node_id] * len(bundles)
        return None

    if strategy in ("SPREAD", "STRICT_SPREAD"):
        placement: List[str] = []
        used: set = set()
        for bundle in bundles:
            candidates = [n.node_id for n in nodes
                          if n.node_id not in used and fits(n.node_id, bundle)]
            if not candidates and strategy == "SPREAD":
                candidates = [n.node_id for n in nodes
                              if fits(n.node_id, bundle)]
            if not candidates:
                return None
            nid = candidates[0]
            placement.append(nid)
            used.add(nid)
            take(nid, bundle)
        return placement

    # PACK (default): greedy first-fit, preferring already-used nodes.
    placement = []
    used_order: List[str] = []
    for bundle in bundles:
        nid = next((u for u in used_order if fits(u, bundle)), None)
        if nid is None:
            nid = next((n.node_id for n in nodes if fits(n.node_id, bundle)),
                       None)
            if nid is None:
                return None
            used_order.append(nid)
        placement.append(nid)
        take(nid, bundle)
    return placement


def all_fit_one(available: ResourceSet, bundles: Sequence[ResourceSet]) -> bool:
    total = ResourceSet()
    for b in bundles:
        total = total + b
    return total.fits(available)
