"""Object serialization.

Equivalent of the reference's python serialization layer
(python/ray/_private/serialization.py + vendored cloudpickle): cloudpickle
with pickle-protocol-5 out-of-band buffers so numpy/jax host arrays round-trip
zero-copy in and out of the shared-memory object store, plus tracking of
ObjectRefs embedded inside serialized values (needed for ownership/refcounting
— the reference tracks "contained object ids" the same way).

Wire format of a serialized object:
    header  = msgpack({"pickle_len": n, "buffer_lens": [...]})-style framing
    payload = pickle_bytes + concat(buffers)
The store keeps payloads as a single contiguous buffer; deserialization maps
buffer views back out-of-band, so a numpy array read from shared memory is a
view over the store's mmap (no copy).

Fast-path framing note: steady-state task pushes do NOT come through this
module at all — the flat wire codec (task_spec.py: template announce +
struct-packed deltas over rpc FLAG_RAW frames) carries them with no pickler
in the loop. This module remains the codec for object VALUES (args bundles,
returns, puts), for control payloads outside the per-call loop (templates
and lease meta blobs encode once per shape via strict `dumps`), and for the
pickle fallback that exotic specs ride.
"""

from __future__ import annotations

import io
import pickle
import struct
import threading
from typing import Any, List, Optional, Tuple

import cloudpickle

_local = threading.local()


class SerializationContext:
    """Collects ObjectRefs encountered while pickling a value."""

    def __init__(self):
        self.contained_refs: List[Any] = []


def get_context() -> Optional[SerializationContext]:
    return getattr(_local, "ctx", None)


class _ContextScope:
    def __enter__(self):
        self.prev = getattr(_local, "ctx", None)
        _local.ctx = SerializationContext()
        return _local.ctx

    def __exit__(self, *exc):
        _local.ctx = self.prev


class SerializedObject:
    __slots__ = ("pickle_bytes", "buffers", "contained_refs")

    def __init__(self, pickle_bytes: bytes, buffers: List[memoryview],
                 contained_refs: List[Any]):
        self.pickle_bytes = pickle_bytes
        self.buffers = buffers
        self.contained_refs = contained_refs

    def total_bytes(self) -> int:
        return (
            9
            + 8 * len(self.buffers)
            + len(self.pickle_bytes)
            + sum(b.nbytes for b in self.buffers)
        )

    def to_bytes(self) -> bytes:
        """Flatten into one contiguous buffer (header + pickle + buffers)."""
        out = bytearray(self.total_bytes())
        self.write_into(memoryview(out))
        return bytes(out)

    def write_into(self, dest: memoryview) -> int:
        """Write the flattened representation into `dest`; returns length."""
        n = len(self.buffers)
        struct.pack_into(">BII", dest, 0, 1, len(self.pickle_bytes), n)
        off = 9
        for b in self.buffers:
            struct.pack_into(">Q", dest, off, b.nbytes)
            off += 8
        end = off + len(self.pickle_bytes)
        dest[off:end] = self.pickle_bytes
        off = end
        for b in self.buffers:
            end = off + b.nbytes
            dest[off:end] = b.cast("B") if b.ndim == 1 else memoryview(bytes(b))
            off = end
        return off


def serialize(value: Any) -> SerializedObject:
    # Plain pickle first (same split as dumps() below): ~4x cheaper than
    # cloudpickle for the common arg shapes (numbers/strings/arrays/
    # framework dataclasses). _StrictPickler refuses anything that would
    # pickle by-reference into `__main__`, so the fallback is safe.
    buffers: List[pickle.PickleBuffer] = []
    data = None
    with _ContextScope() as ctx:
        try:
            bio = io.BytesIO()
            _StrictPickler(bio, protocol=5,
                           buffer_callback=buffers.append).dump(value)
            data = bio.getvalue()
        except Exception:  # noqa: BLE001 — cloudpickle fallback
            data = None
        refs = ctx.contained_refs
    if data is None:
        buffers = []
        with _ContextScope() as ctx:
            data = cloudpickle.dumps(value, protocol=5,
                                     buffer_callback=buffers.append)
            refs = ctx.contained_refs
    views = []
    for pb in buffers:
        try:
            views.append(pb.raw())
        except BufferError:
            views.append(memoryview(bytes(pb)))  # non-contiguous: copy once
    return SerializedObject(data, views, refs)


def deserialize_from_buffer(buf: memoryview) -> Any:
    """Deserialize a flattened object; buffers stay views into `buf`."""
    kind, pickle_len, n = struct.unpack_from(">BII", buf, 0)
    if kind != 1:
        raise ValueError(f"bad serialized object header kind={kind}")
    off = 9
    lens = []
    for _ in range(n):
        (blen,) = struct.unpack_from(">Q", buf, off)
        lens.append(blen)
        off += 8
    data = buf[off : off + pickle_len]
    off += pickle_len
    out_of_band = []
    for blen in lens:
        out_of_band.append(buf[off : off + blen])
        off += blen
    return pickle.loads(data, buffers=out_of_band)


def deserialize(data: bytes) -> Any:
    return deserialize_from_buffer(memoryview(data))


class _NeedsCloudpickle(Exception):
    pass


class _StrictPickler(pickle.Pickler):
    """Plain pickler that refuses anything plain pickle would encode
    by-reference into the sender's `__main__` — the receiver's `__main__`
    is a different module, so such pickles succeed locally but fail to
    load remotely. Refusal triggers the cloudpickle fallback, which
    encodes those by value (cloudpickle's own split, applied eagerly)."""

    def reducer_override(self, obj):
        if isinstance(obj, type) or callable(obj):
            mod = getattr(obj, "__module__", None)
            if mod in (None, "__main__", "__mp_main__"):
                raise _NeedsCloudpickle
        return NotImplemented


def dumps(value: Any) -> bytes:
    """In-band control-plane payload pickle (not user objects).

    Plain pickle first — ~4x cheaper than cloudpickle for the framework
    dataclasses (TaskSpec etc.) that dominate RPC traffic. Payloads
    touching `__main__`-defined classes/functions, closures, or anything
    else plain pickle can't represent portably fall back to cloudpickle."""
    try:
        buf = io.BytesIO()
        _StrictPickler(buf, protocol=5).dump(value)
        return buf.getvalue()
    except Exception:
        return cloudpickle.dumps(value)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


# -- batch-scoped pickling ---------------------------------------------------
# The hot-path pickle rule (rtpulint L006) bans per-CALL picklers on the
# task fast path. These entry points exist for payloads whose pickle
# cost is amortized over a whole batch of completions (one call per
# done-stream flush, never one per task); call sites in hot-path modules
# must still carry a `# batch ok: <why>` annotation, which L006 checks.

def dumps_batch(values: Any) -> bytes:
    """`dumps` for a batch-level payload (one encode per batch)."""
    return dumps(values)


def loads_batch(data: bytes) -> Any:
    """`loads` for a batch-level payload (one decode per batch)."""
    return pickle.loads(data)
