"""Task specifications and function registry.

TaskSpec mirrors the reference's TaskSpecification
(src/ray/common/task/task_spec.h + protobuf/common.proto TaskSpec): the
complete description of one task invocation — identity, function, arguments
(inline bytes or object references), resources, scheduling strategy, retry
policy, actor linkage.

The FunctionManager is the analog of python/ray/_private/function_manager.py:
functions/actor classes are exported once per job into the control-plane KV
store keyed by a content hash; workers load and cache them on first use.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import serialization
from .ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID

# Task types
NORMAL_TASK = "normal"
ACTOR_CREATION_TASK = "actor_creation"
ACTOR_TASK = "actor_task"


@dataclass
class FunctionDescriptor:
    module: str
    qualname: str
    function_id: str  # content hash; KV key of the pickled function

    def display_name(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclass
class TaskArg:
    """One argument: either an inline serialized value or an object ref."""
    is_ref: bool
    data: Optional[bytes] = None          # inline: flattened SerializedObject
    object_id: Optional[ObjectID] = None  # ref
    owner_address: Optional[Tuple[str, int]] = None
    # refs contained inside an inline value (for borrower accounting)
    contained_ref_ids: List[ObjectID] = field(default_factory=list)


@dataclass
class SchedulingStrategy:
    """Normalized scheduling strategy carried in the spec.

    kind: "DEFAULT" | "SPREAD" | "placement_group" | "node_affinity"
          | "node_label"
    """
    kind: str = "DEFAULT"
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False
    node_id: Optional[str] = None       # node_affinity: hex node id
    soft: bool = False                  # node_affinity soft
    label_selector: Dict[str, str] = field(default_factory=dict)


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: str
    function: FunctionDescriptor
    args: List[TaskArg]
    num_returns: int
    resources: Dict[str, float]
    owner_address: Tuple[str, int]
    owner_worker_id: bytes
    name: str = ""
    scheduling_strategy: SchedulingStrategy = field(
        default_factory=SchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: Any = False  # bool or list of exception types (pickled)
    attempt_number: int = 0
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    label_selector: Dict[str, str] = field(default_factory=dict)
    # actor linkage
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    sequence_number: int = -1            # actor task ordering
    max_restarts: int = 0                # actor creation
    max_task_retries: int = 0
    max_concurrency: int = 1
    concurrency_groups: Dict[str, int] = field(default_factory=dict)
    is_asyncio: bool = False
    is_detached: bool = False
    generator_backpressure: int = -1
    enable_task_events: bool = True
    # (trace_id, parent_span_id) from the submitting context — the
    # executing worker opens a child span under it (reference:
    # util/tracing/tracing_helper.py:54-88 injects otel context the
    # same way)
    trace_context: Optional[Tuple[str, str]] = None

    def is_generator(self) -> bool:
        return self.num_returns in ("dynamic", "streaming")

    def return_ids(self) -> List[ObjectID]:
        # Generator tasks own one "generator ref" at index 0; the yielded
        # items land at indices 1..N once N is known (reference:
        # _raylet.pyx ObjectRefGenerator dynamic return ids).
        if self.is_generator():
            return [ObjectID.for_task_return(self.task_id, 0)]
        return [ObjectID.for_task_return(self.task_id, i)
                for i in range(self.num_returns)]

    def shape_key(self) -> Tuple:
        """Lease reuse key: tasks with the same shape share leased workers
        (reference: SchedulingKey in normal_task_submitter.h). Must cover
        the FULL runtime environment — the raylet dedicates workers per
        env (runtime_env_key) and lease handoff between different envs
        would bypass that isolation (stale sys.path/cwd/modules)."""
        return (
            tuple(sorted(self.resources.items())),
            self.scheduling_strategy.kind,
            self.scheduling_strategy.placement_group_id,
            self.scheduling_strategy.bundle_index,
            self.scheduling_strategy.node_id,
            tuple(sorted(self.label_selector.items())),
        ) + runtime_env_key(self.runtime_env)

    def dependencies(self) -> List[Tuple[ObjectID, Tuple[str, int]]]:
        deps = []
        for arg in self.args:
            if arg.is_ref:
                deps.append((arg.object_id, arg.owner_address))
        return deps


class _CallBundle:
    """Bundles (args, kwargs) into one serialized argument; top-level
    ObjectRefs are hoisted into explicit TaskArg deps and replaced by
    placeholders."""
    __slots__ = ("args", "kwargs")

    def __init__(self, args: tuple, kwargs: dict):
        self.args = args
        self.kwargs = kwargs

    def __reduce__(self):
        return (_CallBundle, (self.args, self.kwargs))


class _RefPlaceholder:
    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __reduce__(self):
        return (_RefPlaceholder, (self.index,))


def compute_function_id(pickled: bytes) -> str:
    return hashlib.sha1(pickled).hexdigest()


class FunctionManager:
    """Export/load functions & actor classes through the control-plane KV."""

    NS = "fn"

    def __init__(self, kv_client):
        self._kv = kv_client
        self._lock = threading.Lock()
        self._exported: set = set()
        self._cache: Dict[str, Any] = {}

    def export(self, job_id: JobID, func: Any) -> FunctionDescriptor:
        pickled = serialization.dumps(func)
        fid = compute_function_id(pickled)
        key = f"{job_id.hex()}:{fid}"
        with self._lock:
            if key not in self._exported:
                self._kv.put(self.NS, key, pickled)
                self._exported.add(key)
                self._cache[key] = func
        return FunctionDescriptor(
            module=getattr(func, "__module__", "") or "",
            qualname=getattr(func, "__qualname__", repr(func)),
            function_id=fid,
        )

    def load(self, job_id: JobID, descriptor: FunctionDescriptor) -> Any:
        key = f"{job_id.hex()}:{descriptor.function_id}"
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        pickled = self._kv.get(self.NS, key)
        if pickled is None:
            raise RuntimeError(
                f"function {descriptor.display_name()} not found in registry")
        func = serialization.loads(pickled)
        with self._lock:
            self._cache[key] = func
        return func


# Positional layout shared by the submitter's lease shape key and the
# raylet's worker-pool key: [0] env_vars, [1] working_dir,
# [2] py_modules, [3] pip, [4] python_env requirements, [5] image_uri,
# [6] conda, [7] uv. The raylet's worker spawn reads indices 4-7 — keep
# order append-only.
ENV_KEY_PYTHON_ENV = 4
ENV_KEY_IMAGE_URI = 5
ENV_KEY_CONDA = 6
ENV_KEY_UV = 7


# conda specs normalize through parse_conda_spec (yaml load for file
# paths) — memoized: runtime_env_key runs per task submission.
_conda_key_cache: dict = {}


def _conda_entry(conda) -> "Tuple":
    key = repr(conda)
    stat_key = None
    if isinstance(conda, str) and conda.endswith((".yml", ".yaml")):
        # Path-based specs: repr(path) alone would pin the FIRST parse
        # forever — an edited environment file must produce a new env
        # key. Cache entries are keyed by path and validated against the
        # file's mtime/size (cheap stat per submission), so an edit
        # REPLACES the stale entry instead of leaking it.
        import os
        try:
            stat = os.stat(conda)
            stat_key = (stat.st_mtime_ns, stat.st_size)
        except OSError:
            stat_key = ("missing",)
        cached = _conda_key_cache.get(key)
        if cached is not None and cached[0] == stat_key:
            return cached[1]
    elif key in _conda_key_cache:
        return _conda_key_cache[key][1]
    from .runtime_env import parse_conda_spec
    name, deps = parse_conda_spec(conda)
    entry = ("env", name) if name else ("deps",) + tuple(deps)
    if len(_conda_key_cache) > 256:
        _conda_key_cache.clear()
    _conda_key_cache[key] = (stat_key, entry)
    return entry


def runtime_env_key(runtime_env) -> "Tuple":
    env = runtime_env or {}
    uv = env.get("uv")
    if uv is not None:
        from .runtime_env import normalize_uv
        uv = tuple(normalize_uv(uv))
    return (
        tuple(sorted((env.get("env_vars") or {}).items())),
        env.get("working_dir") or "",
        tuple(env.get("py_modules") or ()),
        tuple(env.get("pip") or ()),
        tuple(sorted((env.get("python_env") or {})
                     .get("requirements", ()))),
        env.get("image_uri") or "",
        _conda_entry(env["conda"]) if env.get("conda") else "",
        uv or "",
    )
