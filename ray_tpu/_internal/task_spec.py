"""Task specifications and function registry.

TaskSpec mirrors the reference's TaskSpecification
(src/ray/common/task/task_spec.h + protobuf/common.proto TaskSpec): the
complete description of one task invocation — identity, function, arguments
(inline bytes or object references), resources, scheduling strategy, retry
policy, actor linkage.

The FunctionManager is the analog of python/ray/_private/function_manager.py:
functions/actor classes are exported once per job into the control-plane KV
store keyed by a content hash; workers load and cache them on first use.
"""

from __future__ import annotations

import hashlib
import logging
import os
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import serialization
from .config import CONFIG
from .ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID

logger = logging.getLogger(__name__)

# Task types
NORMAL_TASK = "normal"
ACTOR_CREATION_TASK = "actor_creation"
ACTOR_TASK = "actor_task"


@dataclass(slots=True)
class FunctionDescriptor:
    module: str
    qualname: str
    function_id: str  # content hash; KV key of the pickled function

    def display_name(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclass(slots=True)
class TaskArg:
    """One argument: either an inline serialized value or an object ref."""
    is_ref: bool
    data: Optional[bytes] = None          # inline: flattened SerializedObject
    object_id: Optional[ObjectID] = None  # ref
    owner_address: Optional[Tuple[str, int]] = None
    # refs contained inside an inline value (for borrower accounting)
    contained_ref_ids: List[ObjectID] = field(default_factory=list)


@dataclass(slots=True)
class SchedulingStrategy:
    """Normalized scheduling strategy carried in the spec.

    kind: "DEFAULT" | "SPREAD" | "placement_group" | "node_affinity"
          | "node_label"
    """
    kind: str = "DEFAULT"
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False
    node_id: Optional[str] = None       # node_affinity: hex node id
    soft: bool = False                  # node_affinity soft
    label_selector: Dict[str, str] = field(default_factory=dict)


# Sender/receiver-local codec state on TaskSpec — never pickled.
_CODEC_LOCAL_FIELDS = ("flat_template", "_shape_key", "_return_ids")


@dataclass(slots=True)
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: str
    function: FunctionDescriptor
    args: List[TaskArg]
    num_returns: int
    resources: Dict[str, float]
    owner_address: Tuple[str, int]
    owner_worker_id: bytes
    name: str = ""
    scheduling_strategy: SchedulingStrategy = field(
        default_factory=SchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: Any = False  # bool or list of exception types (pickled)
    attempt_number: int = 0
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    label_selector: Dict[str, str] = field(default_factory=dict)
    # actor linkage
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    sequence_number: int = -1            # actor task ordering
    max_restarts: int = 0                # actor creation
    max_task_retries: int = 0
    max_concurrency: int = 1
    concurrency_groups: Dict[str, int] = field(default_factory=dict)
    is_asyncio: bool = False
    is_detached: bool = False
    generator_backpressure: int = -1
    enable_task_events: bool = True
    # (trace_id, parent_span_id) from the submitting context — the
    # executing worker opens a child span under it (reference:
    # util/tracing/tracing_helper.py:54-88 injects otel context the
    # same way)
    trace_context: Optional[Tuple[str, str]] = None
    # Flat-wire codec handle: driver-side a SpecTemplate (encode path),
    # worker-side the _Template a decoded spec came from (freelist
    # routing). None -> the spec travels via the pickle fallback.
    flat_template: Any = None
    # Memoized derived values (submit hot path): the shape key sorts
    # three dicts and return_ids builds an ObjectID list — both are
    # invariant for a spec's lifetime (task_id/num_returns never change
    # across retries; resources/env are fixed at construction).
    _shape_key: Optional[Tuple] = None
    _return_ids: Optional[List[ObjectID]] = None

    def __getstate__(self):
        # Codec-local fields stay out of pickles: a fallback-path push
        # must not ship the memoized shape-key tuple / return-id list /
        # template handle the old wire format never carried (they are
        # sender-local caches; receivers rebuild lazily).
        state = {name: getattr(self, name)
                 for name in self.__dataclass_fields__}
        for name in _CODEC_LOCAL_FIELDS:
            state.pop(name, None)
        return state

    def __setstate__(self, state):
        for name in _CODEC_LOCAL_FIELDS:
            setattr(self, name, None)
        for name, value in state.items():
            setattr(self, name, value)

    def is_generator(self) -> bool:
        return self.num_returns in ("dynamic", "streaming")

    def return_ids(self) -> List[ObjectID]:
        # Generator tasks own one "generator ref" at index 0; the yielded
        # items land at indices 1..N once N is known (reference:
        # _raylet.pyx ObjectRefGenerator dynamic return ids).
        ids = self._return_ids
        if ids is None:
            if self.is_generator():
                ids = [ObjectID.for_task_return(self.task_id, 0)]
            else:
                ids = [ObjectID.for_task_return(self.task_id, i)
                       for i in range(self.num_returns)]
            self._return_ids = ids
        return ids

    def shape_key(self) -> Tuple:
        """Lease reuse key: tasks with the same shape share leased workers
        (reference: SchedulingKey in normal_task_submitter.h). Must cover
        the FULL runtime environment — the raylet dedicates workers per
        env (runtime_env_key) and lease handoff between different envs
        would bypass that isolation (stale sys.path/cwd/modules)."""
        key = self._shape_key
        if key is None:
            key = self._shape_key = (
                tuple(sorted(self.resources.items())),
                self.scheduling_strategy.kind,
                self.scheduling_strategy.placement_group_id,
                self.scheduling_strategy.bundle_index,
                self.scheduling_strategy.node_id,
                tuple(sorted(self.label_selector.items())),
            ) + runtime_env_key(self.runtime_env)
        return key

    def dependencies(self) -> List[Tuple[ObjectID, Tuple[str, int]]]:
        deps = []
        for arg in self.args:
            if arg.is_ref:
                deps.append((arg.object_id, arg.owner_address))
        return deps


class _CallBundle:
    """Bundles (args, kwargs) into one serialized argument; top-level
    ObjectRefs are hoisted into explicit TaskArg deps and replaced by
    placeholders."""
    __slots__ = ("args", "kwargs")

    def __init__(self, args: tuple, kwargs: dict):
        self.args = args
        self.kwargs = kwargs

    def __reduce__(self):
        return (_CallBundle, (self.args, self.kwargs))


class _RefPlaceholder:
    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __reduce__(self):
        return (_RefPlaceholder, (self.index,))


def compute_function_id(pickled: bytes) -> str:
    return hashlib.sha1(pickled).hexdigest()


class FunctionManager:
    """Export/load functions & actor classes through the control-plane KV."""

    NS = "fn"

    def __init__(self, kv_client):
        self._kv = kv_client
        self._lock = threading.Lock()
        self._exported: set = set()
        self._cache: Dict[str, Any] = {}

    def export(self, job_id: JobID, func: Any) -> FunctionDescriptor:
        pickled = serialization.dumps(func)
        fid = compute_function_id(pickled)
        key = f"{job_id.hex()}:{fid}"
        with self._lock:
            if key not in self._exported:
                self._kv.put(self.NS, key, pickled)
                self._exported.add(key)
                self._cache[key] = func
        return FunctionDescriptor(
            module=getattr(func, "__module__", "") or "",
            qualname=getattr(func, "__qualname__", repr(func)),
            function_id=fid,
        )

    def load(self, job_id: JobID, descriptor: FunctionDescriptor) -> Any:
        key = f"{job_id.hex()}:{descriptor.function_id}"
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        pickled = self._kv.get(self.NS, key)
        if pickled is None:
            raise RuntimeError(
                f"function {descriptor.display_name()} not found in registry")
        func = serialization.loads(pickled)
        with self._lock:
            self._cache[key] = func
        return func


# Positional layout shared by the submitter's lease shape key and the
# raylet's worker-pool key: [0] env_vars, [1] working_dir,
# [2] py_modules, [3] pip, [4] python_env requirements, [5] image_uri,
# [6] conda, [7] uv. The raylet's worker spawn reads indices 4-7 — keep
# order append-only.
ENV_KEY_PYTHON_ENV = 4
ENV_KEY_IMAGE_URI = 5
ENV_KEY_CONDA = 6
ENV_KEY_UV = 7


# conda specs normalize through parse_conda_spec (yaml load for file
# paths) — memoized: runtime_env_key runs per task submission.
_conda_key_cache: dict = {}


def _conda_entry(conda) -> "Tuple":
    key = repr(conda)
    stat_key = None
    if isinstance(conda, str) and conda.endswith((".yml", ".yaml")):
        # Path-based specs: repr(path) alone would pin the FIRST parse
        # forever — an edited environment file must produce a new env
        # key. Cache entries are keyed by path and validated against the
        # file's mtime/size (cheap stat per submission), so an edit
        # REPLACES the stale entry instead of leaking it.
        import os
        try:
            stat = os.stat(conda)
            stat_key = (stat.st_mtime_ns, stat.st_size)
        except OSError:
            stat_key = ("missing",)
        cached = _conda_key_cache.get(key)
        if cached is not None and cached[0] == stat_key:
            return cached[1]
    elif key in _conda_key_cache:
        return _conda_key_cache[key][1]
    from .runtime_env import parse_conda_spec
    name, deps = parse_conda_spec(conda)
    entry = ("env", name) if name else ("deps",) + tuple(deps)
    if len(_conda_key_cache) > 256:
        _conda_key_cache.clear()
    _conda_key_cache[key] = (stat_key, entry)
    return entry


# ---------------------------------------------------------------------------
# Flat wire codec (reference: the protobuf TaskSpecification in
# src/ray/common/task/task_spec.h is built once and mutated per call —
# this is the same amortization for a pickle-based runtime).
#
# Tasks sharing a shape (same function/method, resources, strategy,
# runtime env, owner) encode their invariant fields ONCE into a
# "template" (pickled, content-addressed by a 16-byte blake2b id) and
# each call ships only a small struct-packed DELTA:
#
#   delta := u8 flags | 24s task_id | i64 sequence_number | u32 attempt
#            [flags&2: u16 len + method_name utf8]            (tombstones)
#            [flags&1: 2x (u16 len + utf8)]                   (trace ctx)
#            u16 n_args, then per arg:
#              0x00 inline: u32 len + data, u16 n_contained + n*28s oids
#              0x01 ref (no owner): 28s object_id
#              0x02 ref: 28s object_id, u16 len + host utf8, u32 port
#
# No pickler runs in the per-call loop on either side. The receiving
# process decodes deltas into __slots__ TaskSpec objects drawn from a
# per-template freelist (constant fields already populated — steady
# state fills only the per-call slots) and returns them to the pool
# once the reply has flushed. Exotic specs (dynamic/streaming returns,
# pickled retry-exception lists) never get a template and transparently
# ride the pickle path instead.
# ---------------------------------------------------------------------------

_TEMPLATE_VERSION = 1
TEMPLATE_ID_LEN = 16

_D_HEAD = struct.Struct("<B24sqI")   # flags, task_id, seq, attempt
_D_U16 = struct.Struct("<H")
_D_U32 = struct.Struct("<I")
_OBJECT_ID_LEN = ObjectID.SIZE
_TASK_ID_LEN = TaskID.SIZE

_DF_TRACE = 1
_DF_METHOD = 2

# TaskSpec fields NOT carried by the template (per-call, or codec-local).
_PER_CALL_FIELDS = ("task_id", "args", "attempt_number", "sequence_number",
                    "trace_context") + _CODEC_LOCAL_FIELDS
_TEMPLATE_FIELDS = tuple(
    name for name in TaskSpec.__dataclass_fields__  # noqa: SLF001
    if name not in _PER_CALL_FIELDS)


# A/B kill switch: RTPU_NO_FLAT_WIRE=1 forces every spec onto the
# pickle path (same-window codec comparisons; read once — hot path).
_NO_FLAT_WIRE = bool(CONFIG.no_flat_wire)


def flat_supported(spec: TaskSpec) -> bool:
    """Fast-path eligibility. Anything else pickles (no behavior change)."""
    if _NO_FLAT_WIRE:
        return False
    return (isinstance(spec.num_returns, int)
            and (spec.retry_exceptions is None
                 or isinstance(spec.retry_exceptions, bool)))


class SpecTemplate:
    """Driver-side handle: the announce bytes + content id for one shape."""

    __slots__ = ("tid", "data", "method_name")

    def __init__(self, tid: bytes, data: bytes, method_name: str):
        self.tid = tid
        self.data = data
        self.method_name = method_name

    def __reduce__(self):
        return (SpecTemplate, (self.tid, self.data, self.method_name))


def make_template(spec: TaskSpec) -> Optional[SpecTemplate]:
    """Build the announce-once template for a spec's shape (None when the
    spec must use the pickle fallback). Called once per handle, not per
    submit."""
    if not flat_supported(spec):
        return None
    # Strict dumps (cloudpickle fallback), not bare pickle: templates
    # encode once per shape, and runtime_env contents are user-supplied —
    # a __main__-defined object must not pickle by reference.
    fields = {name: getattr(spec, name) for name in _TEMPLATE_FIELDS}
    try:
        data = bytes([_TEMPLATE_VERSION]) + serialization.dumps(fields)
    except Exception:  # noqa: BLE001 — unpicklable env etc: fallback
        return None
    tid = hashlib.blake2b(data, digest_size=TEMPLATE_ID_LEN).digest()
    return SpecTemplate(tid, data, spec.method_name)


# The no-arg call bundle is one process-wide TaskArg singleton
# (remote_function.pack_args registers it here); its encoded args
# section is a constant — the dominant flood shape encodes as header +
# one cached bytes append.
_const_arg: Optional[TaskArg] = None
_const_arg_section: Optional[bytes] = None


def register_constant_arg(arg: TaskArg):
    global _const_arg, _const_arg_section
    _const_arg_section = _encode_args([arg])
    _const_arg = arg


def _encode_args(args: List[TaskArg]) -> bytes:
    parts = [_D_U16.pack(len(args))]
    for arg in args:
        if not arg.is_ref:
            data = arg.data
            contained = arg.contained_ref_ids
            parts.append(b"\x00")
            parts.append(_D_U32.pack(len(data)))
            parts.append(data)
            parts.append(_D_U16.pack(len(contained)))
            for oid in contained:
                parts.append(oid.binary())
        elif arg.owner_address is None:
            parts.append(b"\x01")
            parts.append(arg.object_id.binary())
        else:
            host, port = arg.owner_address
            hb = host.encode()
            parts.append(b"\x02")
            parts.append(arg.object_id.binary())
            parts.append(_D_U16.pack(len(hb)))
            parts.append(hb)
            parts.append(_D_U32.pack(port))
    return b"".join(parts)


def encode_delta(spec: TaskSpec, template_method: str) -> bytes:
    """Struct-pack the per-call fields of `spec` (no pickler)."""
    flags = 0
    trace = spec.trace_context
    if trace is not None:
        flags |= _DF_TRACE
    method = spec.method_name
    override = method != template_method
    if override:
        flags |= _DF_METHOD
    parts = [_D_HEAD.pack(flags, spec.task_id.binary(),
                          spec.sequence_number, spec.attempt_number)]
    if override:
        mb = method.encode()
        parts.append(_D_U16.pack(len(mb)))
        parts.append(mb)
    if trace is not None:
        for s in (trace[0], trace[1]):
            sb = s.encode()
            parts.append(_D_U16.pack(len(sb)))
            parts.append(sb)
    args = spec.args
    if len(args) == 1 and args[0] is _const_arg:
        parts.append(_const_arg_section)
    else:
        parts.append(_encode_args(args))
    return b"".join(parts)


def delta_encodable(spec: TaskSpec) -> bool:
    """Per-call bound check against the delta format's u16/u32 fields
    (arg count, inline bytes, contained refs). Oversized calls — which
    the pickle path handles fine — must fall back rather than raise
    struct.error mid-push (that would masquerade as a worker failure)."""
    args = spec.args
    if len(args) == 1 and args[0] is _const_arg:
        return True  # the dominant no-arg shape
    if len(args) > 0xFFFF:
        return False
    for arg in args:
        if not arg.is_ref and (len(arg.data) >= (1 << 32)
                               or len(arg.contained_ref_ids) > 0xFFFF):
            return False
    return True


def peek_task_id(delta: bytes) -> bytes:
    """The raw task-id bytes of a delta — readable WITHOUT the template,
    so an unknown-template failure can still be reported per task."""
    return _D_HEAD.unpack_from(delta, 0)[1]


class _Template:
    """Receiver-side decoded template: prototype field values + the
    freelist of spec objects whose constant slots are already filled."""

    __slots__ = ("tid", "fields", "method_name", "pool",
                 "last_args_raw", "last_args")

    def __init__(self, tid: bytes, fields: Dict[str, Any]):
        self.tid = tid
        self.fields = fields
        self.method_name = fields.get("method_name", "")
        self.pool: List[TaskSpec] = []
        # Memoized last-seen args section: floods repeat one args shape
        # per template (usually the constant no-arg bundle), so decode
        # is a bytes-compare + shared read-only list instead of a parse.
        self.last_args_raw: Optional[bytes] = None
        self.last_args: Optional[List[TaskArg]] = None

    def acquire(self) -> TaskSpec:
        if self.pool:
            return self.pool.pop()
        spec = TaskSpec(task_id=None, args=None, **self.fields)
        spec.flat_template = self
        return spec

    def release(self, spec: TaskSpec):
        if len(self.pool) >= 128:
            return
        # Per-call slots are overwritten on the next acquire; drop the
        # heavy ones now so pooled specs don't pin arg payloads, and
        # undo any tombstone method override.
        spec.args = None
        spec.trace_context = None
        spec._shape_key = None
        spec._return_ids = None
        spec.method_name = self.method_name
        self.pool.append(spec)


_template_lock = threading.Lock()
_templates: Dict[bytes, _Template] = {}
# The host strings in ref-arg owner addresses repeat endlessly; intern.
_host_cache: Dict[bytes, str] = {}


def register_template(tid: bytes, data: bytes):
    _mirror_template(tid)
    with _template_lock:
        if tid in _templates:
            return
    if not data or data[0] != _TEMPLATE_VERSION:
        raise ValueError(f"bad spec template version {data[:1]!r}")
    fields = serialization.loads(data[1:])
    tmpl = _Template(tid, fields)
    with _template_lock:
        if len(_templates) > 4096:
            # Partial eviction (oldest half by insertion order): a full
            # clear() would invalidate templates in active use by every
            # other shape at once — each would then burn a
            # need-template/unknown-template round trip, and re-announces
            # would immediately re-trigger the clear (thrash).
            for old in list(_templates)[:2048]:
                del _templates[old]
        _templates[tid] = tmpl


def _mirror_template(tid: bytes):
    """Keep the C decoder's template mirror (src/fastrpc.cpp) in step
    with this registry, so in-ring decode recognizes shapes announced
    through the pickled/legacy paths too. Soft dependency: decode is an
    optimization, so mirror failure must never fail registration."""
    try:
        from .._native import fastrpc as _native_fastrpc
        _native_fastrpc.mirror_template(tid)
    except Exception:  # noqa: BLE001 — mirror is advisory
        logger.debug("native template mirror failed", exc_info=True)


def lookup_template(tid: bytes) -> Optional[_Template]:
    return _templates.get(tid)


def release_spec(spec: TaskSpec):
    """Return a codec-decoded spec to its freelist (no-op for specs that
    arrived via the pickle path)."""
    tmpl = spec.flat_template
    if type(tmpl) is _Template:
        tmpl.release(spec)


def _intern_host(hb: bytes) -> str:
    host = _host_cache.get(hb)
    if host is None:
        if len(_host_cache) > 1024:
            _host_cache.clear()
        host = _host_cache[hb] = hb.decode()
    return host


def _decode_args(raw: bytes) -> List[TaskArg]:
    (n_args,) = _D_U16.unpack_from(raw, 0)
    off = 2
    args: List[TaskArg] = []
    for _ in range(n_args):
        kind = raw[off]
        off += 1
        if kind == 0:
            (dlen,) = _D_U32.unpack_from(raw, off)
            off += 4
            data = raw[off:off + dlen]
            off += dlen
            (n_cont,) = _D_U16.unpack_from(raw, off)
            off += 2
            contained = []
            for _ in range(n_cont):
                contained.append(ObjectID(raw[off:off + _OBJECT_ID_LEN]))
                off += _OBJECT_ID_LEN
            args.append(TaskArg(is_ref=False, data=data,
                                contained_ref_ids=contained))
        else:
            oid = ObjectID(raw[off:off + _OBJECT_ID_LEN])
            off += _OBJECT_ID_LEN
            owner = None
            if kind == 2:
                (hlen,) = _D_U16.unpack_from(raw, off)
                off += 2
                host = _intern_host(raw[off:off + hlen])
                off += hlen
                (port,) = _D_U32.unpack_from(raw, off)
                off += 4
                owner = (host, port)
            args.append(TaskArg(is_ref=True, object_id=oid,
                                owner_address=owner, contained_ref_ids=[]))
    return args


def decode_delta(delta, tmpl: _Template) -> TaskSpec:
    flags, tid_b, seq, attempt = _D_HEAD.unpack_from(delta, 0)
    off = _D_HEAD.size
    method = None
    if flags & _DF_METHOD:
        (n,) = _D_U16.unpack_from(delta, off)
        off += 2
        method = bytes(delta[off:off + n]).decode()
        off += n
    trace = None
    if flags & _DF_TRACE:
        (n,) = _D_U16.unpack_from(delta, off)
        off += 2
        t0 = bytes(delta[off:off + n]).decode()
        off += n
        (n,) = _D_U16.unpack_from(delta, off)
        off += 2
        trace = (t0, bytes(delta[off:off + n]).decode())
        off += n
    raw_args = bytes(delta[off:])
    return spec_from_fields(tmpl, tid_b, seq, attempt, method, trace,
                            raw_args)


def spec_from_fields(tmpl: _Template, tid_b: bytes, seq: int, attempt: int,
                     method: Optional[str],
                     trace: Optional[Tuple[str, str]],
                     raw_args: bytes) -> TaskSpec:
    """Fill a freelist spec from pre-parsed per-call fields — the
    consumer of the C decoder's DELTAREC records (native_decode.
    parse_delta_record) and the shared tail of decode_delta. The
    template's constant slots are already populated; only the per-call
    slots are written, with the last-seen args section memoized per
    template (floods repeat one args shape, so steady state is a bytes
    compare plus a shared read-only list)."""
    if raw_args == tmpl.last_args_raw:
        # Receiver never mutates arg objects, so identical args bytes
        # (the common flood shape) share one decoded read-only list.
        args = tmpl.last_args
    else:
        args = _decode_args(raw_args)
        tmpl.last_args_raw = raw_args
        tmpl.last_args = args
    spec = tmpl.acquire()
    spec.task_id = TaskID(tid_b)
    spec.sequence_number = seq
    spec.attempt_number = attempt
    spec.args = args
    spec.trace_context = trace
    if method is not None:
        spec.method_name = method
    return spec


def runtime_env_key(runtime_env) -> "Tuple":
    env = runtime_env or {}
    uv = env.get("uv")
    if uv is not None:
        from .runtime_env import normalize_uv
        uv = tuple(normalize_uv(uv))
    return (
        tuple(sorted((env.get("env_vars") or {}).items())),
        env.get("working_dir") or "",
        tuple(env.get("py_modules") or ()),
        tuple(env.get("pip") or ()),
        tuple(sorted((env.get("python_env") or {})
                     .get("requirements", ()))),
        env.get("image_uri") or "",
        _conda_entry(env["conda"]) if env.get("conda") else "",
        uv or "",
    )
