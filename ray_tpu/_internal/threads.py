"""Daemon-thread registry: every background daemon thread in ray_tpu is
created through (or registered with) this module so node teardown can
stop and join them with a bounded timeout instead of abandoning them —
and so rtpulint rule L005 can verify the invariant statically.

Three lifecycles:

* ``spawn_daemon(target, stop=ev.set)`` — loop threads that poll a
  ``threading.Event``; teardown calls ``stop`` then joins.
* ``spawn_daemon(target)`` / ``joinable=False`` — threads whose exit is
  driven elsewhere (fd close, short-lived one-shot work, the
  process-lifetime io loop). Tracked for introspection, never joined.
* ``register_daemon_thread(t, ...)`` — same, for threads a component
  must construct itself.

``shutdown_daemon_threads()`` is called from ``Node.stop()``; entries
that joined (or died on their own) are pruned, so a later ``init()`` in
the same process restarts its singletons cleanly.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_entries: List["_Entry"] = []


@dataclass
class _Entry:
    thread: threading.Thread
    stop: Optional[Callable[[], None]]
    joinable: bool


def register_daemon_thread(thread: threading.Thread,
                           stop: Optional[Callable[[], None]] = None,
                           joinable: Optional[bool] = None) -> threading.Thread:
    """Track ``thread`` for bounded teardown. ``stop`` is invoked before
    joining (typically ``Event.set`` breaking the thread's sleep loop).
    ``joinable`` defaults to ``stop is not None`` — joining a thread with
    no stop signal would just burn the teardown budget."""
    if joinable is None:
        joinable = stop is not None
    with _lock:
        _prune_locked()
        _entries.append(_Entry(thread, stop, joinable))
    return thread


def spawn_daemon(target: Callable, *, name: Optional[str] = None,
                 args: tuple = (),
                 stop: Optional[Callable[[], None]] = None,
                 joinable: Optional[bool] = None) -> threading.Thread:
    """Create, register, and start a daemon thread in one step."""
    t = threading.Thread(target=target, args=args, daemon=True, name=name)
    register_daemon_thread(t, stop=stop, joinable=joinable)
    t.start()
    return t


def _prune_locked():
    # ident is None until start(): keep not-yet-started registrations.
    _entries[:] = [e for e in _entries
                   if e.thread.ident is None or e.thread.is_alive()]


def alive_daemon_threads() -> List[threading.Thread]:
    with _lock:
        _prune_locked()
        return [e.thread for e in _entries]


def shutdown_daemon_threads(timeout_s: float = 2.0) -> List[str]:
    """Signal every registered stop hook, then join joinable threads
    within one shared ``timeout_s`` budget. Returns the names of threads
    still alive afterwards (logged, not raised — teardown must finish)."""
    import time
    with _lock:
        _prune_locked()
        entries = list(_entries)
    for e in entries:
        if e.stop is not None:
            try:
                e.stop()
            except Exception:
                logger.exception("daemon thread %s stop hook failed",
                                 e.thread.name)
    deadline = time.monotonic() + timeout_s
    stuck: List[str] = []
    for e in entries:
        # ident None = registered but never started (or start() raised):
        # join() would raise RuntimeError and abort the teardown sweep.
        if not e.joinable or e.thread.ident is None:
            continue
        e.thread.join(max(0.0, deadline - time.monotonic()))
        if e.thread.is_alive():
            stuck.append(e.thread.name or "<unnamed>")
    if stuck:
        logger.warning("daemon threads still alive after %.1fs teardown "
                       "budget: %s", timeout_s, stuck)
    with _lock:
        _prune_locked()
    return stuck
