"""Worker process entrypoint
(reference: python/ray/_private/workers/default_worker.py).

Spawned by a raylet's worker pool. Registers back with the raylet, then
serves `push_task` RPCs on its CoreWorker until killed, told to exit, or its
raylet disappears (a dead raylet orphans the worker — exit so nodes die
cleanly in fault-tolerance tests).

Task frames arrive on the flat wire path (see task_spec's codec): the
first push of each shape announces a template, every later push is a
struct-packed delta decoded into a `__slots__` TaskSpec drawn from the
template's freelist and returned to it once the reply has flushed — the
steady-state execution loop runs with no pickler and no spec allocation.
`RTPU_NO_FLAT_WIRE=1` (driver-side) forces the legacy pickled specs for
A/B runs; this worker serves both forms.
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys
import time


def main():
    # Log & forensics plane: stamp every stdout/stderr line and logging
    # record with (task, actor, job, level) BEFORE anything writes —
    # the raylet's pump parses the stamps into its per-worker ring.
    # install_worker_capture puts a level-stamping handler on the root
    # logger (same format as the basicConfig below, which then no-ops);
    # under RTPU_NO_LOG_PLANE it installs nothing and basicConfig runs
    # exactly as before.
    from .logplane import install_worker_capture
    install_worker_capture()
    logging.basicConfig(
        level=logging.INFO,
        format="[worker %(process)d] %(levelname)s %(name)s: %(message)s")
    # `kill -USR2 <pid>` dumps every thread's stack to stderr (reference:
    # the dashboard's on-demand py-spy; this is the dependency-free
    # always-on variant for debugging wedged workers).
    import faulthandler
    import signal
    try:
        faulthandler.register(signal.SIGUSR2, all_threads=True)
    except (AttributeError, ValueError):
        pass
    # RTPU_SANITIZE=1 (inherited from the raylet) instruments this
    # worker's locks too — must run before any ray_tpu lock exists.
    from .lint import sanitizer as _sanitizer
    _sanitizer.enable_from_env()
    if os.environ.get("RTPU_WORKER_PROFILE"):
        # Dev/profiling hook: dump the io-loop thread's cProfile stats on
        # SIGUSR1 to RTPU_WORKER_PROFILE/<pid>.prof.
        _install_profile_hook(os.environ["RTPU_WORKER_PROFILE"])
    worker_id = bytes.fromhex(os.environ["RTPU_WORKER_ID"])
    session = os.environ["RTPU_SESSION"]
    node_id = os.environ["RTPU_NODE_ID"]
    node_index = int(os.environ["RTPU_NODE_INDEX"])
    raylet_host, raylet_port = os.environ["RTPU_RAYLET_ADDR"].rsplit(":", 1)
    gcs_host, gcs_port = os.environ["RTPU_GCS_ADDR"].rsplit(":", 1)
    raylet_addr = (raylet_host, int(raylet_port))
    gcs_addr = (gcs_host, int(gcs_port))

    from .core_worker import CoreWorker, set_core_worker
    from .rpc import EventLoopThread
    # Warm the flat-wire codec (struct tables + template registry) before
    # the first push lands, keeping import cost off the first task.
    from . import task_spec as _codec  # noqa: F401

    worker = CoreWorker(
        mode="worker", session_name=session, gcs_address=gcs_addr,
        raylet_address=raylet_addr, node_id=node_id, node_index=node_index,
        worker_id=worker_id)
    worker.start()
    set_core_worker(worker)

    raylet = worker.clients.get(raylet_addr)
    reply = raylet.call_sync(
        "register_worker", worker_id=worker_id,
        address=worker.rpc_address, pid=os.getpid(), retries=5)
    if reply.get("exit"):
        sys.exit(0)

    # Stay alive while the raylet does. The raylet is our parent process,
    # so reparenting (getppid changes) is the authoritative death signal —
    # it is immune to event-loop starvation, which on a 1-core box can
    # stall RPC pings for tens of seconds during worker-spawn bursts.
    # Pings remain as a slow fallback for a wedged-but-alive raylet.
    parent = os.getppid()
    ping_misses = 0
    last_ping = time.monotonic()
    while True:
        time.sleep(2.0)
        if os.getppid() != parent:
            logging.getLogger(__name__).warning(
                "raylet process gone; worker exiting")
            os._exit(1)
        if time.monotonic() - last_ping >= 10.0:
            last_ping = time.monotonic()
            try:
                raylet.call_sync("ping", timeout=10, retries=0)
                ping_misses = 0
            except Exception:
                ping_misses += 1
                if ping_misses >= 30:  # ~5 min of continuous failure
                    logging.getLogger(__name__).warning(
                        "raylet unresponsive for ~5min; worker exiting")
                    os._exit(1)


def _install_profile_hook(out_dir: str):
    import cProfile
    import pstats
    import signal

    from .rpc import EventLoopThread

    # One FRESH Profile per toggle cycle: reusing a single instance
    # across cycles accumulated stats forever, and a fixed <pid>.prof
    # overwrote the previous cycle's dump — each cycle now stands alone
    # under a timestamped filename.
    state = {"prof": None}

    def toggle(_sig, _frm):
        loop = EventLoopThread.get().loop
        if state["prof"] is None:
            prof = state["prof"] = cProfile.Profile()
            loop.call_soon_threadsafe(prof.enable)
        else:
            prof, state["prof"] = state["prof"], None

            def dump(prof=prof):
                os.makedirs(out_dir, exist_ok=True)
                stamp = time.strftime("%Y%m%d-%H%M%S")
                path = os.path.join(
                    out_dir, f"{os.getpid()}-{stamp}.prof")
                with open(path, "w") as f:
                    pstats.Stats(prof, stream=f).sort_stats(
                        "cumulative").print_stats(40)

            def disable_then_dump(prof=prof):
                # disable and the dump hand-off run as ONE loop
                # callback: spawning the dump thread before the loop
                # has executed disable() would let pstats walk timing
                # entries the still-profiled loop thread is mutating
                prof.disable()
                from .threads import spawn_daemon
                spawn_daemon(dump, name="rtpu-profile-dump")
            loop.call_soon_threadsafe(disable_then_dump)
    signal.signal(signal.SIGUSR1, toggle)


if __name__ == "__main__":
    main()
