"""Build-on-first-use for the C++ native components.

The image bakes g++ but not pybind11, so native code exposes a C ABI and
Python binds with ctypes. The shared library is compiled once per source
hash into a cache dir; concurrent builders race benignly via a unique tmp
name + rename."""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import tempfile
from typing import Optional

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "src")
_CACHE_DIR = os.environ.get(
    "RTPU_NATIVE_CACHE", os.path.expanduser("~/.cache/rtpu-native"))


def build_library(name: str, debug: Optional[bool] = None) -> Optional[str]:
    """Compile src/<name>.cpp into a cached .so; returns its path or None
    if the toolchain is unavailable/failing (callers fall back to the
    pure-Python path).

    ``debug`` (default: the RTPU_NATIVE_DEBUG env toggle) builds a
    sanitizer variant — ``-fsanitize=address,undefined -g`` — cached
    under its own name. Loading it requires libasan to be preloaded
    (see tests/test_native_decode.py's smoke test, which runs a
    subprocess with LD_PRELOAD), so the debug build is a diagnosis
    tool, not a production transport: C decode bugs surface as ASAN
    reports instead of corrupted specs."""
    if debug is None:
        debug = bool(os.environ.get("RTPU_NATIVE_DEBUG"))
    src = os.path.join(_SRC_DIR, f"{name}.cpp")
    try:
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None
    suffix = "-dbg" if debug else ""
    out = os.path.join(_CACHE_DIR, f"{name}-{digest}{suffix}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_CACHE_DIR, exist_ok=True)
    tmp = tempfile.mktemp(prefix=f"{name}-", suffix=".so",
                          dir=_CACHE_DIR)
    if debug:
        flags = ["-O1", "-g", "-fsanitize=address,undefined",
                 "-fno-omit-frame-pointer"]
    else:
        flags = ["-O2"]
    cmd = (["g++"] + flags +
           ["-shared", "-fPIC", "-std=c++17", "-pthread", src, "-o", tmp])
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build unavailable (%s); using python "
                       "fallback", e)
        return None
    if proc.returncode != 0:
        logger.warning("native build of %s failed:\n%s", name,
                       proc.stderr.decode()[-2000:])
        return None
    os.replace(tmp, out)
    return out
